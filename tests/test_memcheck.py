"""Runtime memory-budget sanitizer tests (TTD_MEMCHECK=1).

conftest arms the sanitizer for the WHOLE tier-1 suite — these tests
pin that (a) the annotated package allocators really are instrumented,
(b) the ACCEPTANCE criterion: an over-budget ``--kv-pool-blocks``
engine raises ``MemoryBudgetError`` with the allocation diffed against
the live set at the REAL serving path's first pool allocation — before
any XLA OOM, (c) admission's projected-bytes check refuses requests
whose marginal bytes cannot fit the declared budget (alongside the
free-blocks check), (d) the ledger's lifetimes behave (leaf death
releases, owner replacement, owner-gc purge), (e) memory events land
in the flight recorder, the trace_report table, and the labeled
``ttd_engine_hbm_bytes{pool=...}`` gauge family — per worker through
the subprocess stats-frame relay, (f) the ``TTD_NO_MEMCHECK`` escape
hatch works LIVE, and (g) the per-allocation overhead stays inside a
measured bar (the lockcheck <25 us/acquire discipline, scaled to the
per-admission path this wrapper sits on).
"""

import gc
import os
import time

import jax
import jax.numpy as jnp
import pytest

from tensorflow_train_distributed_tpu.runtime import events
from tensorflow_train_distributed_tpu.runtime.lint import memcheck
from tensorflow_train_distributed_tpu.runtime.lint.memcheck import (
    MemoryBudgetError,
)
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    memory_budget,
)


def _llama_engine(**kw):
    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
        LlamaModel,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS["llama_tiny"]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]
    kw.setdefault("slots", 2)
    kw.setdefault("cache_len", 32)
    kw.setdefault("chunk", 2)
    kw.setdefault("prompt_buckets", (8,))
    return ServingEngine(cfg, params, **kw)


# ── the package really is instrumented in tier-1 ───────────────────────


def test_conftest_armed_and_package_sites_registered():
    assert memcheck.armed(), "conftest should arm TTD_MEMCHECK"
    import tensorflow_train_distributed_tpu.serving  # noqa: F401
    import tensorflow_train_distributed_tpu.training.trainer  # noqa: F401

    sites = memcheck.sites()
    for site in ("serving.ServingEngine._fresh_cache",
                 "serving.ServingEngine._admission_cache_1",
                 "trainer.Trainer.create_state"):
        assert site in sites, f"{site} not registered (got {sites})"
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    assert getattr(ServingEngine._fresh_cache,
                   "__ttd_memcheck_wrapped__", False)


def test_env_flags_spelled_for_audit():
    """TTD_MEMCHECK / TTD_NO_MEMCHECK drive this whole module via
    conftest; assert the arming env is what we think it is."""
    assert os.environ.get("TTD_MEMCHECK") == "1"
    assert os.environ.get("TTD_NO_MEMCHECK") in (None, "", "0")


# ── toy-allocator ledger mechanics ─────────────────────────────────────


class _Owner:
    pass


@memory_budget(pool="test_pool", budget_fn=lambda self, n: self.budget,
               lifetime="leaf")
def _leaf_alloc(self, n):
    return [jnp.zeros((n,), jnp.float32)]


@memory_budget(pool="test_pinned",
               budget_fn=lambda self, n: self.budget)
def _owner_alloc(self, n):
    return [jnp.zeros((n,), jnp.float32)]


def test_budget_raises_before_known_signature_reallocates():
    owner = _Owner()
    owner.budget = 10_000
    kept = _leaf_alloc(owner, 512)          # 2048 B, fine
    assert memcheck.live_bytes(owner=owner) == 2048
    with pytest.raises(MemoryBudgetError) as ei:
        _leaf_alloc(owner, 4096)            # 16 KiB > budget
    msg = str(ei.value)
    # The offending allocation, diffed against the live set.
    assert "test_pool" in msg and "budget" in msg
    assert "live test_pool" in msg          # the kept 2 KiB listed
    del kept


def test_leaf_death_releases_the_charge():
    owner = _Owner()
    owner.budget = None                      # track-only
    kept = _leaf_alloc(owner, 256)
    assert memcheck.live_bytes(owner=owner) == 1024
    del kept
    gc.collect()
    assert memcheck.live_bytes(owner=owner) == 0


def test_owner_lifetime_replaces_instead_of_double_counting():
    owner = _Owner()
    owner.budget = None
    _owner_alloc(owner, 256)
    _owner_alloc(owner, 256)                 # rebuilt: replaces
    assert memcheck.live_bytes(owner=owner) == 1024
    gc.collect()                             # buffers died; owner pins
    assert memcheck.live_bytes(owner=owner) == 1024


def test_owner_rebuild_within_budget_does_not_double_count():
    """Regression (review pass): the pre-allocation budget check used
    to count BOTH the existing owner-lifetime charge and the rebuild
    about to replace it — any pool/state rebuild with budget < 2x the
    allocation spuriously raised."""
    owner = _Owner()
    owner.budget = 1500
    _owner_alloc(owner, 256)                 # 1024 B
    _owner_alloc(owner, 256)                 # rebuild: net stays 1024
    assert memcheck.live_bytes(owner=owner) == 1024


def test_owner_gc_purges_the_ledger():
    owner = _Owner()
    owner.budget = None
    _owner_alloc(owner, 256)
    before = memcheck.live_bytes(pool="test_pinned")
    assert before >= 1024
    tok = ("tok", owner.__ttd_mc_token__)
    assert any(k[1] == tok for k in memcheck._PROJ)
    del owner
    gc.collect()
    assert memcheck.live_bytes(pool="test_pinned") < before
    # The projection memo purges with the ledger (review pass: the
    # leak-catcher must not itself leak per dead owner).
    assert not any(k[1] == tok for k in memcheck._PROJ)


def test_track_charges_stored_trees_and_enforces():
    rec = events.get_recorder()
    rec.clear()
    owner = _Owner()
    tree = [jnp.zeros((128,), jnp.float32)]
    tree2 = [jnp.zeros((128,), jnp.float32)]
    memcheck.track(owner, "tracked_pool", tree, label="stored")
    memcheck.track(owner, "tracked_pool", tree2, label="stored2")
    assert memcheck.live_bytes(owner=owner, pool="tracked_pool") == 1024
    # The instants carry the pool's LIVE total, not just one entry's
    # bytes (review pass: trace_report's live/peak columns would
    # otherwise understate a 10-entry prefix store by 10x).
    insts = [e for e in rec.events() if e[0] == "memory/tracked_pool"]
    assert [e[5]["live"] for e in insts] == [512, 1024]
    with pytest.raises(MemoryBudgetError):
        memcheck.track(owner, "tracked_pool",
                       [jnp.zeros((1024,), jnp.float32)],
                       label="leak", budget=1024)
    del tree, tree2
    gc.collect()


def test_tree_bytes_is_host_metadata():
    struct = {"a": jax.ShapeDtypeStruct((4, 8), jnp.int8),
              "b": jnp.zeros((2, 2), jnp.float32)}
    assert memcheck.tree_bytes(struct) == 4 * 8 + 16


# ── the acceptance path: over-budget --kv-pool-blocks ──────────────────


def test_over_budget_kv_pool_raises_before_oom():
    """The acceptance criterion: an engine whose oversized
    ``kv_pool_blocks`` cannot fit its declared ``hbm_budget_bytes``
    raises ``MemoryBudgetError`` at the REAL serving path's first pool
    allocation — projected from the cache eval_shape BEFORE the
    buffers exist, with the overshoot spelled out — instead of an
    opaque XLA OOM mid-session."""
    eng = _llama_engine(kv_pool_blocks=4096,
                        hbm_budget_bytes=2_000_000)
    assert eng.kv_pool_bytes() > eng.hbm_budget_bytes
    eng.submit([1, 2, 3], 4)                # marginal bytes fit: admitted
    with pytest.raises(MemoryBudgetError) as ei:
        eng.run()
    msg = str(ei.value)
    assert "kv_pool" in msg and "_fresh_cache" in msg
    assert "budget" in msg


def test_within_budget_engine_serves_and_gauges_render():
    eng = _llama_engine(hbm_budget_bytes=64 * 1024 * 1024)
    rid = eng.submit([1, 2, 3], 4)
    out = eng.run()
    assert len(out[rid]) == 7
    pools = memcheck.live_by_pool()
    assert pools.get("kv_pool", 0) >= eng.kv_pool_bytes()
    # THIS engine's ledgered kv_pool agrees with its own constant to
    # within the block-table/index leaves (the global gauge may also
    # carry other live engines' pools in a full-suite run).
    mine = memcheck.live_bytes(owner=eng, pool="kv_pool")
    assert (eng.kv_pool_bytes() <= mine
            < eng.kv_pool_bytes() * 1.01 + 4096)


def test_admission_refuses_on_projected_bytes():
    """The closed loop: validate_request refuses a request whose
    marginal prefill bytes cannot fit the declared budget — the
    projected-bytes check alongside the free-blocks check."""
    eng = _llama_engine(hbm_budget_bytes=1)
    with pytest.raises(ValueError, match="projected"):
        eng.validate_request([1, 2, 3], 4)


def test_kv_block_pool_reports_bytes():
    eng = _llama_engine()
    pool = eng._kv_pool
    assert pool.bytes_per_block > 0
    assert pool.bytes_total() == pool.n_blocks * pool.bytes_per_block
    assert pool.bytes_in_use() == (pool.blocks_in_use()
                                   * pool.bytes_per_block)
    # Long enough that a FULL block (block_size 16) outlives retire in
    # the radix cache: 8 prompt + 12 generated = 20 tokens → 16 cached.
    eng.submit(list(range(1, 9)), 12)
    eng.run()
    # Retired blocks stay radix-cached: the engine's byte occupancy
    # accessor (the /healthz + worker-gauge consumer) reports them.
    assert eng.kv_bytes_in_use() == (pool.blocks_in_use()
                                     * pool.bytes_per_block) > 0


# ── observability: spans, trace_report, gauges, worker relay ───────────


def test_memory_spans_land_in_flight_recorder():
    rec = events.get_recorder()
    rec.clear()
    owner = _Owner()
    owner.budget = None
    kept = _leaf_alloc(owner, 64)
    spans = [e for e in rec.events() if e[0] == "memory/test_pool"]
    assert len(spans) == 1
    name, ph, t0, dur, tid, attrs = spans[0]
    assert ph == "X"
    assert attrs["pool"] == "test_pool"
    assert attrs["bytes"] == 256
    assert attrs["live"] >= 256
    del kept


def test_near_miss_instant_past_90_percent():
    rec = events.get_recorder()
    rec.clear()
    owner = _Owner()
    owner.budget = 1100
    kept = _leaf_alloc(owner, 256)          # 1024 B > 0.9 * 1100
    miss = [e for e in rec.events() if e[0] == "memory/near_miss"]
    assert len(miss) == 1
    assert miss[0][5]["pool"] == "test_pool"
    assert miss[0][5]["budget"] == 1100
    del kept


def test_trace_report_folds_memory_spans():
    from tools.trace_report import memory_summary

    rec = events.get_recorder()
    rec.clear()
    owner = _Owner()
    owner.budget = 8192
    kept = _leaf_alloc(owner, 512)
    evs = rec.export_chrome_trace()["traceEvents"]
    table = memory_summary(evs)
    assert "test_pool" in table
    row = table["test_pool"]
    assert row["allocs"] == 1
    assert row["peak_live"] >= 2048
    assert row["budget"] == 8192
    del kept


def test_metrics_labeled_gauge_renders_pools():
    from tensorflow_train_distributed_tpu.server.metrics import (
        GatewayMetrics,
    )

    owner = _Owner()
    owner.budget = None
    kept = _leaf_alloc(owner, 128)
    m = GatewayMetrics(lambda: 0, lambda: 0, 1)
    rendered = m.render()
    assert "ttd_engine_hbm_bytes" in rendered
    assert 'ttd_engine_hbm_bytes{pool="test_pool"}' in rendered
    del kept


def test_remote_engine_relays_worker_hbm():
    """The stats-frame relay: a subprocess worker ships its memcheck
    ledger per frame; the parent facade exposes it and the pool labels
    it per worker — ttd_engine_kv_pool_bytes rides the same frames."""
    from tensorflow_train_distributed_tpu.server.procpool import (
        RemoteEngine,
    )

    eng = RemoteEngine()
    eng.update_stats({"gauges": {"kv_pool_bytes": 4096.0},
                      "hbm": {"kv_pool": 4096.0,
                              "prefill_cache": 64.0},
                      "rss": 1})
    assert eng.kv_pool_bytes() == 4096.0
    assert eng.hbm_by_pool() == {"kv_pool": 4096.0,
                                 "prefill_cache": 64.0}


def test_pool_labels_hbm_per_worker():
    """A pool of subprocess replicas renders each worker's pools as
    "<replica>/<pool>" — fleet memory visible PER WORKER; a pool of
    in-process replicas falls back to this process's global ledger."""
    from tensorflow_train_distributed_tpu.server.replicas import (
        ReplicaPool,
    )

    class _Eng:
        def __init__(self, hbm):
            self._hbm = hbm

        def hbm_by_pool(self):
            return dict(self._hbm)

    class _Rep:
        def __init__(self, idx, hbm):
            self.idx = idx
            self.engine = _Eng(hbm)

        def usable(self):
            return True

    fake = type("_FakePool", (), {})()
    fake._replicas = [_Rep(0, {"kv_pool": 100.0}),
                      _Rep(3, {"kv_pool": 200.0, "prefill_cache": 5.0})]
    out = ReplicaPool.hbm_by_pool(fake)
    assert out == {"0/kv_pool": 100.0, "3/kv_pool": 200.0,
                   "3/prefill_cache": 5.0}
    # In-process replicas (no facade): the process ledger is the view.
    owner = _Owner()
    owner.budget = None
    kept = _leaf_alloc(owner, 16)
    fake._replicas = [type("_R", (), {
        "idx": 0, "engine": object(),
        "usable": lambda self: True})()]
    out = ReplicaPool.hbm_by_pool(fake)
    assert out.get("test_pool", 0) >= 64
    del kept


def test_worker_stats_frame_carries_hbm_and_kv_pool_bytes():
    from tensorflow_train_distributed_tpu.server import worker

    class _Sender:
        gone = False

        def __init__(self):
            self.frames = []

        def send(self, ftype, body):
            self.frames.append((ftype, body))
            return True

    class _Driver:
        def waiting(self):
            return 0

        def active_slots(self):
            return 0

        def steps_completed(self):
            return 0

        def step_elapsed(self):
            return 0.0

        def alive(self):
            return True

        def is_draining(self):
            return False

        def failure(self):
            return None

    eng = _llama_engine()
    owner = _Owner()
    owner.budget = None
    kept = _leaf_alloc(owner, 32)
    sender = _Sender()
    worker._send_stats(_Driver(), eng, sender, 0, False)
    _, body = sender.frames[-1]
    assert body["gauges"]["kv_pool_bytes"] == eng.kv_pool_bytes()
    assert body["gauges"]["kv_bytes_in_use"] == eng.kv_bytes_in_use()
    assert body["hbm"].get("test_pool", 0) >= 128
    del kept


# ── escape hatch + overhead bar ────────────────────────────────────────


def test_no_memcheck_escape_hatch_is_live(monkeypatch):
    """Unlike arming (decoration-time), the veto is re-read per
    allocation: an operator can disarm a misbehaving sanitizer with an
    env flip, no redeploy."""
    owner = _Owner()
    owner.budget = 64
    monkeypatch.setenv("TTD_NO_MEMCHECK", "1")
    assert not memcheck.armed()
    kept = _leaf_alloc(owner, 4096)         # would raise; vetoed through
    assert memcheck.live_bytes(owner=owner) == 0   # and never charged
    monkeypatch.delenv("TTD_NO_MEMCHECK")
    assert memcheck.armed()
    with pytest.raises(MemoryBudgetError):
        _leaf_alloc(owner, 4096)
    del kept


def test_overhead_bar_per_allocation():
    """The measured bar conftest's suite-wide arming rides on: the
    wrapper's bookkeeping per allocation — signature memo hit, budget
    check, ledger charge, one weakref finalizer per minted leaf, the
    memory span — measured ~68 us on this host (difference of wrapped
    vs unwrapped legs, best of 5).  The bar is 4x the measured value:
    this sits on the per-ADMISSION path (once per request, never per
    token), where even 250 us is noise against a ~ms prefill — but an
    accidental O(ledger) scan or per-leaf stringification regression
    lands far above it."""
    owner = _Owner()
    owner.budget = 1 << 30
    inner = _leaf_alloc.__wrapped__
    _leaf_alloc(owner, 8)                   # memoize the signature
    n = 1000
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            _leaf_alloc(owner, 8)
        t1 = time.perf_counter()
        for _ in range(n):
            inner(owner, 8)
        t2 = time.perf_counter()
        best = min(best, ((t1 - t0) - (t2 - t1)) / n)
    per_op = max(0.0, best)
    assert per_op < 250e-6, f"{per_op * 1e6:.2f} us/alloc overhead"


def test_trainer_state_pool_charges(mesh8):
    """The trainer's create_state charges pool "trainer_state" with
    the full state bytes (params + opt moments), projected from the
    abstract state BEFORE materialization — and an over-budget config
    raises with nothing allocated."""
    import numpy as np
    import optax

    import flax.linen as nn

    from tensorflow_train_distributed_tpu.training.trainer import (
        Trainer,
        TrainerConfig,
    )

    class _MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    class _Task:
        def __init__(self):
            self.model = _MLP()

        def init_variables(self, rng, batch):
            return self.model.init(rng, jnp.zeros(batch["x"].shape,
                                                  jnp.float32))

        def loss_fn(self, params, model_state, batch, rng, train):
            out = self.model.apply({"params": params}, batch["x"])
            return (out ** 2).mean(), ({}, model_state)

    batch = {"x": np.zeros((8, 4), np.float32)}
    trainer = Trainer(_Task(), optax.adam(1e-2), mesh8,
                      config=TrainerConfig())
    state = trainer.create_state(batch)
    live = memcheck.live_bytes(owner=trainer, pool="trainer_state")
    assert live > 0
    # Adam state ≈ params + 2 moments (+ scalars): the charge is the
    # real state, not a placeholder.
    n_param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(state.params))
    assert live >= 3 * n_param_bytes
    tight = Trainer(_Task(), optax.adam(1e-2), mesh8,
                    config=TrainerConfig(hbm_budget_bytes=8))
    with pytest.raises(MemoryBudgetError, match="trainer_state"):
        tight.create_state(batch)
