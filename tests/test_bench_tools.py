"""Bench tooling: the HBM pre-flight guard and the shared timing path.

The guard exists because an HBM-OOM compile request can kill the
single-chip TPU tunnel for the whole session (PROFILE.md) — these tests
pin its calibration to the three measured v5e data points and its
skip-off-TPU contract, with fake device objects (no backend needed).
"""

import dataclasses
import importlib.util
import os

import pytest

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture(scope="module")
def bench_lm_mod():
    spec = importlib.util.spec_from_file_location(
        "bench_lm_under_test", os.path.join(_TOOLS, "bench_lm.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@dataclasses.dataclass
class FakeDevice:
    platform: str = "tpu"
    device_kind: str = "TPU v5 lite"


LLAMA_125M = dict(n_params=134_105_856, n_layers=12, d_model=768, seq=2048)


class TestHbmGuard:
    def test_measured_v5e_points(self, bench_lm_mod):
        """Calibration: b8 no-remat ran on the chip, b16 no-remat OOMed
        at 26.4 GiB (both measured 2026-07-30), remat always fits."""
        check = bench_lm_mod.check_hbm_budget
        dev = FakeDevice()
        check(batch=8, remat=False, causal=True, force=False, device=dev,
              **LLAMA_125M)  # fits → returns
        check(batch=8, remat=True, causal=True, force=False, device=dev,
              **LLAMA_125M)
        with pytest.raises(SystemExit):
            check(batch=16, remat=False, causal=True, force=False,
                  device=dev, **LLAMA_125M)

    def test_skipped_off_tpu_and_on_unknown_kind(self, bench_lm_mod):
        for dev in (FakeDevice(platform="cpu", device_kind="cpu"),
                    FakeDevice(device_kind="TPU v99 mystery")):
            bench_lm_mod.check_hbm_budget(
                batch=4096, remat=False, causal=True, force=False,
                device=dev, **LLAMA_125M)  # must not raise

    def test_force_overrides(self, bench_lm_mod):
        bench_lm_mod.check_hbm_budget(
            batch=4096, remat=False, causal=True, force=True,
            device=FakeDevice(), **LLAMA_125M)

    def test_generation_budgets(self, bench_lm_mod):
        """llama_1b no-remat (state ~17 GiB) refuses on v5e, fits v5p."""
        kw = dict(n_params=1_300_000_000, n_layers=16, d_model=2048,
                  batch=4, seq=2048, remat=False, causal=True, force=False)
        with pytest.raises(SystemExit):
            bench_lm_mod.check_hbm_budget(
                device=FakeDevice(device_kind="TPU v5 lite"), **kw)
        bench_lm_mod.check_hbm_budget(
            device=FakeDevice(device_kind="TPU v5p"), **kw)

    def test_per_head_scores_matter(self, bench_lm_mod):
        """BERT-style einsum attention (score_heads=num_heads) refuses a
        config the flash-path model would wave through."""
        kw = dict(n_params=110_000_000, n_layers=12, d_model=768,
                  batch=32, seq=512, remat=False, causal=False,
                  force=False, device=FakeDevice())
        bench_lm_mod.check_hbm_budget(score_heads=1, **kw)
        with pytest.raises(SystemExit):
            bench_lm_mod.check_hbm_budget(score_heads=12, **kw)

    def test_refusal_record_is_json(self, bench_lm_mod, capsys):
        import json

        with pytest.raises(SystemExit):
            bench_lm_mod.check_hbm_budget(
                batch=4096, remat=False, causal=True, force=False,
                device=FakeDevice(), **LLAMA_125M)
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert "error" in rec and rec["estimated_gib"] > rec["budget_gib"]


def test_bench_bert_smoke_on_cpu_mesh(bench_lm_mod):
    """End-to-end tiny BERT bench on the test mesh (conftest forces CPU):
    the record schema the docstring promises actually lands."""
    spec = importlib.util.spec_from_file_location(
        "bench_bert_under_test", os.path.join(_TOOLS, "bench_bert.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rec = mod.bench_bert("bert_tiny", batch=2, seq=32, warmup=1, iters=2)
    assert rec["unit"] == "samples/sec/chip"
    assert rec["value"] > 0 and rec["backend"] == "cpu"
    assert rec["n_params"] > 0


def test_bench_generate_cpu_smoke():
    """Decode-throughput tool: full prefill+scan path on CPU, one JSON
    record with the required fields."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "bench_generate.py"),
         "--preset", "llama_tiny", "--batch", "2", "--prompt-len", "16",
         "--max-new", "16", "--iters", "2", "--platform", "cpu"],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    assert rec["unit"] == "tokens/sec/chip"
    assert rec["backend"] == "cpu"
    assert rec["max_new_tokens"] == 16


def test_bench_generate_int8_cpu_smoke():
    """--quant int8 runs the weight-only serving path end-to-end and
    stamps the record."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "bench_generate.py"),
         "--preset", "llama_tiny", "--batch", "2", "--prompt-len", "16",
         "--max-new", "16", "--iters", "2", "--platform", "cpu",
         "--quant", "int8"],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    assert rec["quant"] == "int8"


def test_bench_generate_rejects_max_new_one():
    """--max-new 1 cannot measure a decode rate (it IS the prefill call);
    argparse rejects it cleanly instead of a ZeroDivisionError."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "bench_generate.py"),
         "--preset", "llama_tiny", "--max-new", "1", "--platform", "cpu"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 2  # argparse usage error
    assert "--max-new must be >= 2" in out.stderr


def test_bench_input_cpu_smoke():
    """Input-pipeline bench: all modes produce positive rates."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "bench_input.py"),
         "--records", "64", "--image-hw", "64", "--size", "32",
         "--batch", "16", "--workers", "2"],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert set(rec["modes"]) == {"inprocess", "inprocess_u8", "workers2",
                                 "mmap_predecoded"}
    assert all(v > 0 for v in rec["modes"].values())
    assert rec["decode_modes"]["pil"] > 0
    if any(k.startswith("native") for k in rec["decode_modes"]):
        assert rec["decode_modes"]["native_t1"] > 0


def test_bench_moe_cpu_smoke():
    """MoE train-throughput tool: full jitted step on CPU, one JSON
    record with active-param accounting."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "bench_moe.py"),
         "--preset", "moe_tiny", "--batch-per-chip", "4", "--seq", "64",
         "--iters", "2", "--platform", "cpu"],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    assert 0 < rec["n_active_params"] < rec["n_params"]


def test_bench_generate_moe_preset_cpu_smoke():
    """MoE presets decode through the same bench path (generate's
    config dispatch); llama-only flags are rejected for them."""
    import json
    import subprocess
    import sys

    base = [sys.executable, os.path.join(_TOOLS, "bench_generate.py"),
            "--preset", "moe_tiny", "--batch", "2", "--prompt-len", "8",
            "--max-new", "8", "--iters", "2", "--platform", "cpu"]
    out = subprocess.run(base, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0
    out = subprocess.run(base + ["--kv-cache", "int8"],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode != 0
    assert "llama-family" in (out.stderr + out.stdout)


def test_bench_emit_headline_is_bounded_and_last(tmp_path, monkeypatch):
    """Driver tail-capture contract (VERDICT r4 item 2): whatever the
    record size, bench.py's LAST stdout line is a compact parseable
    headline — BENCH_r04 recorded parsed:null because one fat line
    (full last_known_tpu embed) overflowed the driver's capture."""
    import io
    import json
    from contextlib import redirect_stdout

    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(
            os.path.dirname(_TOOLS), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # Keep the repo's real last_emit.json (live driver/hunter artifact)
    # out of the test's blast radius.
    monkeypatch.setattr(bench, "FULL_EMIT_PATH",
                        str(tmp_path / "last_emit.json"))

    fat = {
        "metric": bench.HEADLINE_METRIC, "value": 1.0,
        "unit": "images/sec/chip", "vs_baseline": 0.0,
        "backend": "cpu", "fallback": True,
        "error": "x" * 500,
        "configs": {f"cfg{i}": {"v": i, "pad": "y" * 400}
                    for i in range(30)},
        "last_known_tpu": {
            "metric": bench.HEADLINE_METRIC, "value": 2436.1,
            "unit": "images/sec/chip", "vs_baseline": 0.974,
            "mfu_pct": 15.2, "backend": "tpu",
            "configs": {f"cfg{i}": {"v": i, "pad": "z" * 400}
                        for i in range(20)},
        },
    }
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._emit(fat)
    lines = buf.getvalue().strip().splitlines()
    head = json.loads(lines[-1])          # last line parses
    assert len(lines[-1]) < 1000          # and is bounded
    assert head["value"] == 1.0 and head["fallback"] is True
    assert head["last_known_tpu"]["value"] == 2436.1
    assert "configs" not in head["last_known_tpu"]
    assert len(head["error"]) <= 160
    # No other stdout line exceeds the sane-line bound (fat full record
    # is diverted to the persisted file, referenced by a comment line).
    assert all(len(ln) <= bench._MAX_FULL_LINE for ln in lines)
    # Full record persisted verbatim for archaeology.
    with open(bench.FULL_EMIT_PATH) as f:
        assert json.load(f)["error"] == "x" * 500


# ── decode MBU fields (the serving benches' shared byte model) ─────────


class TestDecodeMbuFields:
    """``bench_gateway.decode_mbu_fields`` — the model-bandwidth
    companion every serving record now carries: the byte model follows
    bench_generate's convention (cast params once + the slot-grid KV
    working set per decode step; int8 halves rows and adds f32
    scales), and off-TPU ``mbu_pct`` is honestly null, never a made-up
    number."""

    @pytest.fixture(scope="class")
    def mbu_mod(self):
        spec = importlib.util.spec_from_file_location(
            "bench_gateway_under_test",
            os.path.join(_TOOLS, "bench_gateway.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    @pytest.fixture(scope="class")
    def cfg(self):
        from tensorflow_train_distributed_tpu.models.llama import (
            LLAMA_PRESETS,
        )

        return LLAMA_PRESETS["llama_tiny"]

    def test_byte_model_and_cpu_null(self, mbu_mod, cfg):
        import jax.numpy as jnp

        n_params, slots, rows = 1000, 4, 64
        out = mbu_mod.decode_mbu_fields(cfg, n_params, slots, rows,
                                        tokens_per_sec=100.0)
        itemsize = jnp.dtype(cfg.dtype).itemsize
        kvh = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.d_model // cfg.num_heads
        want = (n_params * itemsize
                + 2 * cfg.num_layers * slots * rows * kvh * hd
                * itemsize)
        assert out["decode_bytes_per_step"] == want
        assert out["mbu_pct"] is None      # CPU: no bandwidth table

    def test_int8_halves_rows_adds_scales(self, mbu_mod, cfg):
        import jax.numpy as jnp

        n_params, slots, rows = 1000, 4, 64
        fp = mbu_mod.decode_mbu_fields(cfg, n_params, slots, rows,
                                       100.0)
        q8 = mbu_mod.decode_mbu_fields(cfg, n_params, slots, rows,
                                       100.0, kv_int8=True)
        itemsize = jnp.dtype(cfg.dtype).itemsize
        kvh = cfg.num_kv_heads or cfg.num_heads
        hd = cfg.d_model // cfg.num_heads
        kv_rows = 2 * cfg.num_layers * slots * rows * kvh
        assert (fp["decode_bytes_per_step"]
                - q8["decode_bytes_per_step"]
                == kv_rows * hd * (itemsize - 1) - kv_rows * 4)
