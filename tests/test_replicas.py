"""Multi-replica serving tests: pool routing, health/watchdog,
deterministic failover, retry-with-backoff, staged drain, chaos parity.

Fast tier drives the ``ReplicaPool`` (and the full HTTP gateway over
it) with the deterministic ``StubEngine`` from test_gateway — death,
vanish, and hang faults are injected through ``runtime.faults``'s
``serve:dispatch`` site so every failure mode is reproducible.  The
real-engine tests pin the headline contract: with one of two replicas
killed mid-decode, every accepted request completes on the survivor
with a token stream EQUAL to an uninterrupted single-replica run
(greedy and seeded sampling), and ``TTD_NO_FAILOVER=1`` restores the
single-engine gateway byte-for-byte.
"""

import json
import threading
import time

import pytest

from tensorflow_train_distributed_tpu.runtime import events, faults
from tensorflow_train_distributed_tpu.server import (
    AdmissionFull,
    DeadlineExceeded,
    ServingGateway,
)
from tensorflow_train_distributed_tpu.server.replicas import ReplicaPool
from test_gateway import StubEngine, _get, _parse_prom, _post


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.disarm()


def _stub_pool(n=2, *, slots=2, step_delay=0.01, **kw):
    kw.setdefault("watchdog_timeout_s", 2.0)
    return ReplicaPool([StubEngine(slots=slots, step_delay=step_delay)
                        for _ in range(n)], **kw).start()


# ── fault-plan grammar ─────────────────────────────────────────────────


def test_serve_dispatch_fault_plan_parses_and_rejects():
    plan = faults.parse_plan(
        "serve:dispatch:5:kill9:replica=1;"
        "serve:dispatch:3:hang:hang_s=0.5;serve:dispatch:2:raise")
    assert [e.site for e in plan.entries] == ["serve:dispatch"] * 3
    assert plan.entries[0].params["replica"] == 1
    with pytest.raises(ValueError, match="unknown serve action"):
        faults.parse_plan("serve:dispatch:5:sigterm")
    with pytest.raises(ValueError, match="not an integer"):
        faults.parse_plan("serve:dispatch:x:raise")


# ── pool basics ────────────────────────────────────────────────────────


def test_pool_serves_concurrent_requests_exactly():
    pool = _stub_pool(2)
    try:
        hs = [pool.submit([10 * (i + 1)], 3 + i % 4) for i in range(8)]
        for i, h in enumerate(hs):
            expect = StubEngine.expected([10 * (i + 1)], 3 + i % 4)
            assert h.result(timeout=10) == expect
            assert pool.request_status(h.id) == "ok"
        assert pool.alive_count() == 2
    finally:
        assert pool.join(timeout=10)


def test_pool_affinity_routes_shared_prefix_to_one_replica():
    """Two requests sharing a first KV block (16 stub tokens) land on
    the same replica — the warm-prefix routing policy."""
    pool = _stub_pool(2, step_delay=0.02)
    try:
        shared = list(range(1, 17))            # one full default block
        h1 = pool.submit(shared + [99], 30)
        deadline = time.monotonic() + 5
        while pool.active_slots() == 0:        # placed and decoding
            assert time.monotonic() < deadline
            time.sleep(0.005)
        first_rep = next(r for r in pool.replicas
                         if r.driver.active_slots()
                         + r.driver.waiting() > 0)
        h2 = pool.submit(shared + [77], 2)
        assert h2.result(timeout=10) == StubEngine.expected(
            shared + [77], 2)
        assert first_rep.affinity(tuple(shared)) == 1
        # The follow-up was routed to the replica that saw the prefix
        # even though the other one was idle.
        assert h1.result(timeout=20) == StubEngine.expected(
            shared + [99], 30)
        states = pool.replica_states()
        others = [s for s in states if s["replica"] != first_rep.idx]
        assert all(s["queue_depth"] == 0 and s["slots_in_use"] == 0
                   for s in others)
    finally:
        assert pool.join(timeout=10)


# ── failover: the three death modes ────────────────────────────────────


class DiesAfter(StubEngine):
    """Stub whose serve_step raises after ``n`` steps (driver-death
    with error propagation — the 'device exploded' mode)."""

    def __init__(self, n, slots=2, step_delay=0.01):
        super().__init__(slots=slots, step_delay=step_delay)
        self.n = n
        self.steps = 0

    def serve_step(self):
        self.steps += 1
        if self.steps > self.n:
            raise RuntimeError("replica exploded")
        return super().serve_step()


def test_failover_on_driver_death_completes_exactly():
    pool = ReplicaPool(
        [DiesAfter(3), StubEngine(slots=2, step_delay=0.01)],
        max_queue=16, watchdog_timeout_s=2.0).start()
    try:
        hs = [pool.submit([7 + i], 40) for i in range(4)]
        for i, h in enumerate(hs):
            assert h.result(timeout=30) == StubEngine.expected(
                [7 + i], 40), i
        states = pool.replica_states()
        assert sum(s["state"] == "dead" for s in states) == 1
        assert pool.alive_count() == 1
    finally:
        pool.join(timeout=10)


def test_failover_on_kill9_vanish_and_timeline_shows_hop():
    """kill9 = abrupt vanish: no error propagates, only the liveness
    monitor notices; every request still completes exactly, and the
    flight recorder shows both lives plus the failover hop."""
    faults.arm("serve:dispatch:3:kill9:replica=0")
    pool = _stub_pool(2)
    try:
        hs = [pool.submit([3 + i], 30) for i in range(4)]
        for i, h in enumerate(hs):
            assert h.result(timeout=30) == StubEngine.expected(
                [3 + i], 30), i
        dead = [r for r in pool.replicas if r.dead]
        assert len(dead) == 1 and dead[0].idx == 0
        assert dead[0].driver.vanished()
        assert dead[0].driver.failure() is None    # no corpse: SIGKILL
        # At least one request hopped; its timeline shows admission on
        # replica 0, the failover instant, re-admission on replica 1.
        hopped = None
        for h in hs:
            names = [e[0] for e in
                     events.get_recorder().request_timeline(h.id)]
            if "request/failover" in names:
                hopped = h
                tl = events.get_recorder().request_timeline(h.id)
                break
        assert hopped is not None, "no request failed over?"
        reps_of_admits = [
            (e[5] or {}).get("replica") for e in tl
            if e[0] == "request/admitted"]
        assert reps_of_admits == [0, 1]
        names = [e[0] for e in tl]
        assert names.index("request/pool_admitted") < names.index(
            "request/failover") < names.index("request/pool_retire")
    finally:
        faults.disarm()
        pool.join(timeout=10)


def test_failover_on_hung_dispatch_watchdog():
    """A wedged decode dispatch (hang fault) trips the watchdog: the
    replica is declared dead while its thread still exists, and its
    requests resume on the survivor."""
    faults.arm("serve:dispatch:3:hang:replica=0:hang_s=20")
    pool = _stub_pool(2, watchdog_timeout_s=0.4)
    try:
        hs = [pool.submit([5 + i], 30) for i in range(4)]
        t0 = time.monotonic()
        for i, h in enumerate(hs):
            assert h.result(timeout=30) == StubEngine.expected(
                [5 + i], 30), i
        # Detection is watchdog-bounded, nowhere near hang_s.
        assert time.monotonic() - t0 < 10
        dead = [r for r in pool.replicas if r.dead]
        assert len(dead) == 1 and dead[0].idx == 0
        assert "watchdog" in dead[0].dead_reason
    finally:
        faults.disarm()
        pool.join(timeout=10)


def test_dead_replica_driver_is_fenced_after_wake():
    """A hung dispatch that WAKES after the watchdog declared its
    replica dead must not dispatch again: the pool poisons the driver
    at declaration, so the woken loop exits instead of working its
    stale backlog — a zombie driving the device (or consuming a
    later-armed chaos-fault budget, the flake this regression pins)
    corrupts whoever took over."""
    faults.arm("serve:dispatch:2:hang:replica=0:hang_s=1.5")
    pool = _stub_pool(2, watchdog_timeout_s=0.3)
    try:
        hs = [pool.submit([3 + i], 20) for i in range(4)]
        for i, h in enumerate(hs):
            assert h.result(timeout=30) == StubEngine.expected(
                [3 + i], 20), i
        dead = [r for r in pool.replicas if r.dead]
        assert len(dead) == 1 and dead[0].idx == 0
        drv = dead[0].driver
        # The wedged thread wakes from the hang and must EXIT —
        # unfenced it would decode its whole failed-over backlog and
        # then wait on the condition forever (this join times out).
        drv._thread.join(timeout=10)
        assert not drv._thread.is_alive()
        # ...without completing more than the step it was wedged in
        # (unfenced, the backlog adds dozens of completed steps).
        assert drv.steps_completed() <= 3, drv.steps_completed()
    finally:
        faults.disarm()
        pool.join(timeout=10)


def test_unscoped_serve_fault_fires_on_every_replica():
    """A serve:dispatch entry WITHOUT replica= kills every driver —
    each has its own fire budget (N drivers must not race one shared
    budget and leave N-1 replicas unscathed)."""
    faults.arm("serve:dispatch:2:raise")
    pool = _stub_pool(2, slots=1, step_delay=0.01)
    try:
        hs = [pool.submit([4 + i], 20) for i in range(4)]
        for h in hs:
            with pytest.raises(RuntimeError):
                h.result(timeout=20)
        deadline = time.monotonic() + 5
        while not all(r.dead for r in pool.replicas):
            assert time.monotonic() < deadline, pool.replica_states()
            time.sleep(0.01)
        assert pool.alive_count() == 0
    finally:
        faults.disarm()
        pool.join(timeout=10)


def test_no_replicas_left_fails_cleanly():
    """Both replicas dying mid-flight resolves (not hangs) every
    request with an error, and later submissions raise NoReplicas."""
    from tensorflow_train_distributed_tpu.server.replicas import (
        NoReplicas,
    )

    pool = ReplicaPool([DiesAfter(2), DiesAfter(2)], max_queue=16,
                       watchdog_timeout_s=2.0).start()
    try:
        hs = [pool.submit([9 + i], 50, timeout_s=60.0)
              for i in range(3)]
        t0 = time.monotonic()
        for h in hs:
            with pytest.raises(RuntimeError):
                h.result(timeout=20)
        assert time.monotonic() - t0 < 15      # fail-fast, not deadline
        deadline = time.monotonic() + 5
        while pool.alive():
            assert time.monotonic() < deadline
            time.sleep(0.01)
        with pytest.raises(NoReplicas):
            pool.submit([1], 1)
        assert pool.failure() is not None
    finally:
        pool.join(timeout=10)


# ── retry with backoff (transient admission refusals) ──────────────────


def _gw_metrics_for(pool):
    from tensorflow_train_distributed_tpu.server.metrics import (
        GatewayMetrics,
    )

    m = GatewayMetrics(queue_depth_fn=pool.waiting,
                       slots_in_use_fn=pool.active_slots,
                       slots_total=4,
                       replicas_alive_fn=pool.alive_count)
    pool.set_metrics(m)
    return m


def _fill_replica(rep, prompt, max_new, n=2, timeout=5.0):
    """Saturate one replica directly through its driver: n requests,
    waiting out the admission races (the driver loop moves work into
    the engine asynchronously)."""
    handles = []
    deadline = time.monotonic() + timeout
    while len(handles) < n:
        try:
            handles.append(rep.driver.submit(list(prompt), max_new))
        except AdmissionFull:
            assert time.monotonic() < deadline, "replica never drained"
            time.sleep(0.005)
    return handles


def test_placement_retries_with_backoff_instead_of_failing_fast():
    """Every replica's own queue full at submit time: the request is
    NOT shed — placement retries with backoff and completes once a
    queue drains; the retries counter counts the waits."""
    pool = ReplicaPool(
        [StubEngine(slots=1, step_delay=0.01) for _ in range(2)],
        max_queue=64, replica_max_queue=1, backoff_base_s=0.02,
        watchdog_timeout_s=5.0).start()
    m = _gw_metrics_for(pool)
    try:
        # Saturate both replicas through their own drivers: 1 decoding
        # + 1 queued each (replica_max_queue=1).
        direct = [h for i, rep in enumerate(pool.replicas)
                  for h in _fill_replica(rep, [1 + i], 30)]
        h = pool.submit([40], 2, timeout_s=30.0)
        assert h.result(timeout=30) == StubEngine.expected([40], 2)
        assert m.retries.value() >= 1
        assert m.requests.value(label_value="shed") == 0
        for d in direct:
            assert d.result(timeout=30)
    finally:
        pool.join(timeout=10)


def test_placement_gives_up_at_deadline_with_expired_status():
    """Queues that never drain: the retry loop gives up exactly at the
    request's deadline with DeadlineExceeded (status 'expired'), not a
    fail-fast refusal and not an infinite spin."""
    pool = ReplicaPool(
        [StubEngine(slots=1, step_delay=0.05) for _ in range(2)],
        max_queue=64, replica_max_queue=1, backoff_base_s=0.02,
        watchdog_timeout_s=5.0).start()
    m = _gw_metrics_for(pool)
    try:
        direct = [h for i, rep in enumerate(pool.replicas)
                  for h in _fill_replica(rep, [1 + i], 500)]
        h = pool.submit([40], 2, timeout_s=0.4)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=10)
        assert 0.2 < time.monotonic() - t0 < 5
        assert pool.request_status(h.id) == "expired"
        assert m.retries.value() >= 2           # backed off repeatedly
        assert m.requests.value(label_value="expired") == 1
        for d in direct:                # free the stub slots for drain
            d.deadline = time.monotonic()
    finally:
        pool.join(timeout=20)


def test_pool_level_shed_still_answers_admission_full():
    """The pool-wide bound still sheds: 2 decoding + 2 queued fills
    max_queue=2 worth of WAITING work, and the next submission gets
    AdmissionFull with the configured Retry-After."""
    pool = ReplicaPool(
        [StubEngine(slots=1, step_delay=0.05) for _ in range(2)],
        max_queue=2, retry_after_s=3.0, watchdog_timeout_s=5.0).start()
    try:
        hs = [pool.submit([5 + i], 100) for i in range(2)]
        deadline = time.monotonic() + 5
        while pool.active_slots() < 2:    # both decoding, waiting == 0
            assert time.monotonic() < deadline
            time.sleep(0.005)
        hs += [pool.submit([7 + i], 100) for i in range(2)]
        while pool.waiting() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        with pytest.raises(AdmissionFull) as ei:
            pool.submit([9], 1)
        assert ei.value.retry_after_s == 3.0
        for h in hs:
            pool.abandon(h)
    finally:
        pool.join(timeout=20)


# ── staged drain ───────────────────────────────────────────────────────


def test_pool_drain_is_staged_and_finishes_inflight():
    """join() drains replicas one at a time: in-flight work on BOTH
    replicas completes, new submissions are refused, and the pool
    reports fully drained."""
    from tensorflow_train_distributed_tpu.server.driver import Draining

    pool = _stub_pool(2, slots=1, step_delay=0.02)
    try:
        hs = [pool.submit([6 + i], 40) for i in range(2)]
        deadline = time.monotonic() + 5
        while pool.active_slots() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        drainer = threading.Thread(target=pool.join, args=(20,))
        drainer.start()
        deadline = time.monotonic() + 5
        while not pool.is_draining():
            assert time.monotonic() < deadline
            time.sleep(0.005)
        with pytest.raises(Draining):
            pool.submit([1], 1)
        for i, h in enumerate(hs):
            assert h.result(timeout=20) == StubEngine.expected(
                [6 + i], 40)
        drainer.join(timeout=20)
        assert not drainer.is_alive()
    finally:
        pool.join(timeout=10)


# ── gateway over the pool (HTTP) ───────────────────────────────────────


def _make_pool_gateway(engines=None, **kw):
    engines = engines or [StubEngine(slots=2, step_delay=0.01)
                          for _ in range(2)]
    kw.setdefault("watchdog_timeout_s", 2.0)
    return ServingGateway(engines, host="127.0.0.1", port=0,
                          **kw).start()


def test_gateway_pool_healthz_metrics_and_failover():
    faults.arm("serve:dispatch:4:kill9:replica=0")
    gw = _make_pool_gateway()
    try:
        status, body, _ = _get(gw.port, "/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok"
        assert health["replicas_alive"] == 2
        assert [r["replica"] for r in health["replicas"]] == [0, 1]

        results = [None] * 5

        def client(i):
            results[i] = _post(gw.port, {"prompt": [11 * (i + 1)],
                                         "max_new": 25})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, (status, obj, _) in enumerate(results):
            assert status == 200, (i, status, obj)
            assert obj["tokens"] == StubEngine.expected(
                [11 * (i + 1)], 25)
        # Degraded — NOT 503: one replica still serves.
        status, body, _ = _get(gw.port, "/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "degraded"
        assert health["replicas_alive"] == 1
        dead = [r for r in health["replicas"] if r["state"] == "dead"]
        assert len(dead) == 1 and dead[0]["replica"] == 0
        s = _parse_prom(_get(gw.port, "/metrics")[1])
        assert s["ttd_gateway_replicas_alive"] == 1
        assert s["ttd_gateway_failovers_total"] >= 1
        assert s['ttd_gateway_requests_total{status="ok"}'] == 5
        # No token duplicated or dropped across the hop.
        assert s["ttd_gateway_tokens_generated_total"] == 5 * 25
    finally:
        faults.disarm()
        gw.drain(timeout=15)


def test_gateway_overload_sheds_with_retry_after_and_expires_visibly():
    """Overload coverage: all replicas saturated → the pool-full shed
    carries Retry-After; a deadline-bound admitted request expires
    with 504 and an 'expired' terminal status in its timeline; and
    NOTHING is silently dropped — every submission is accounted
    ok|shed|expired."""
    gw = _make_pool_gateway(
        [StubEngine(slots=1, step_delay=0.05) for _ in range(2)],
        max_queue=4, retry_after_s=2.0)
    try:
        outcomes = []
        lock = threading.Lock()

        def client(i, max_new, timeout_s=None):
            body = {"prompt": [5 + i], "max_new": max_new}
            if timeout_s is not None:
                body["timeout_s"] = timeout_s
            status, obj, headers = _post(gw.port, body)
            with lock:
                outcomes.append((status, obj, headers))

        # Two long requests take both single-slot replicas...
        long_t = [threading.Thread(target=client, args=(i, 50))
                  for i in range(2)]
        for t in long_t:
            t.start()
        deadline = time.monotonic() + 5
        while gw.driver.active_slots() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # ...two more fill each replica's queue share
        # (replica_max_queue = max_queue/2 = 2 → 1 decoding + 2
        # queued... fill both replica queues and the pool bound).
        fill_t = [threading.Thread(target=client, args=(2 + i, 2))
                  for i in range(2)]
        for t in fill_t:
            t.start()
        deadline = time.monotonic() + 5
        while gw.driver.waiting() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # A deadline-bound request and one more filler bring waiting to
        # the pool bound (4)...
        t_exp = threading.Thread(target=client, args=(4, 100, 1.0))
        t_exp.start()
        extra_t = threading.Thread(target=client, args=(5, 2))
        extra_t.start()
        deadline = time.monotonic() + 5
        while gw.driver.waiting() < 4:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # ...so the NEXT submission is shed, with Retry-After.
        status, obj, headers = _post(gw.port, {"prompt": [99],
                                               "max_new": 1})
        assert status == 429
        assert int(headers["Retry-After"]) == 2
        assert "error" in obj
        for t in long_t + fill_t + [t_exp, extra_t]:
            t.join()
        statuses = sorted(s for s, _, _ in outcomes)
        assert statuses == [200, 200, 200, 200, 200, 504], statuses
        s = _parse_prom(_get(gw.port, "/metrics")[1])
        assert s['ttd_gateway_requests_total{status="ok"}'] == 5
        assert s['ttd_gateway_requests_total{status="shed"}'] == 1
        assert s['ttd_gateway_requests_total{status="expired"}'] == 1
        # The expired request's timeline records the terminal status.
        expired_ids = [
            rid for rid in range(6)
            if gw.driver.request_status(rid) == "expired"]
        assert len(expired_ids) == 1
        status, body, _ = _get(gw.port,
                               f"/v1/requests/{expired_ids[0]}")
        assert status == 200
        assert json.loads(body)["status"] == "expired"
    finally:
        gw.drain(timeout=20)


def test_gateway_all_replicas_dead_answers_503_with_retry_after():
    gw = _make_pool_gateway([DiesAfter(1, slots=1), DiesAfter(1, slots=1)])
    try:
        _post(gw.port, {"prompt": [1], "max_new": 10})  # detonate both
        deadline = time.monotonic() + 10
        while gw.pool.alive_count() > 0:
            _post(gw.port, {"prompt": [1], "max_new": 2})
            assert time.monotonic() < deadline
            time.sleep(0.02)
        status, obj, headers = _post(gw.port, {"prompt": [2],
                                               "max_new": 1})
        assert status == 503
        assert "Retry-After" in headers
        status, body, _ = _get(gw.port, "/healthz")
        assert status == 503
        assert json.loads(body)["status"] == "no_replicas"
        s = _parse_prom(_get(gw.port, "/metrics")[1])
        assert s["ttd_gateway_replicas_alive"] == 0
    finally:
        gw._httpd.shutdown()
        gw._httpd.server_close()


def test_gateway_sigterm_drain_staged_n2():
    """The single-engine SIGTERM drain contract extended to N=2:
    /healthz flips to draining (503), new submissions refused, both
    replicas' in-flight requests finish."""
    gw = _make_pool_gateway(
        [StubEngine(slots=1, step_delay=0.02) for _ in range(2)])
    try:
        inflight = {}

        def client(name, prompt):
            inflight[name] = _post(gw.port, {"prompt": prompt,
                                             "max_new": 50})

        threads = [threading.Thread(target=client, args=(f"r{i}", [2 + i]))
                   for i in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while gw.driver.active_slots() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        drainer = threading.Thread(target=gw.drain, args=(20,))
        drainer.start()
        deadline = time.monotonic() + 5
        while not gw.draining:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        status, body, _ = _get(gw.port, "/healthz")
        assert status == 503
        assert json.loads(body)["status"] == "draining"
        status, obj, _ = _post(gw.port, {"prompt": [1], "max_new": 1})
        assert status == 503
        for t in threads:
            t.join()
        drainer.join()
        for i in range(2):
            status, obj, _ = inflight[f"r{i}"]
            assert status == 200
            assert obj["tokens"] == StubEngine.expected([2 + i], 50)
    finally:
        if not gw._stopped.is_set():
            gw.drain(timeout=10)


# ── real engine: resume-from-token + chaos failover parity ─────────────


@pytest.fixture(scope="module")
def llama_tiny():
    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
        LlamaModel,
    )

    cfg = LLAMA_PRESETS["llama_tiny"]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, params


def _engine_kw(sampling):
    kw = dict(slots=2, cache_len=64, chunk=4, prompt_buckets=(8, 16, 32))
    if sampling:
        kw.update(temperature=0.8, top_k=40)
    return kw


@pytest.mark.parametrize("sampling", [False, True],
                         ids=["greedy", "seeded-sampling"])
def test_engine_resume_from_token_is_bitwise(llama_tiny, sampling):
    """The failover primitive: re-admitting prompt + g generated
    tokens with resume_from=g continues the EXACT token stream an
    uninterrupted run produces (the rng counter picks up at g)."""
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg, params = llama_tiny
    kw = _engine_kw(sampling)
    prompt, max_new, seed = [5, 9, 2, 7], 12, 123
    eng = ServingEngine(cfg, params, **kw)
    rid = eng.submit(prompt, max_new, seed=seed if sampling else None)
    ref = eng.run()[rid]
    for g in (1, 3, 7):
        eng2 = ServingEngine(cfg, params, **kw)
        rid2 = eng2.submit(ref[:len(prompt) + g], max_new - g,
                           seed=seed if sampling else None,
                           resume_from=g)
        assert eng2.run()[rid2] == ref, g


def test_resume_beyond_largest_bucket_is_admitted(llama_tiny):
    """A resumed prompt (original + streamed tokens) may exceed the
    largest prefill bucket the ORIGINAL admission fit in — the resumed
    tail is the request's own output and ``_pieces_for`` chunks any
    span into bucket-sized pieces, so re-admission must not die
    'invalid' mid-failover (and the continuation stays bitwise)."""
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg, params = llama_tiny
    kw = dict(slots=2, cache_len=64, chunk=4, prompt_buckets=(8,))
    prompt, max_new = [5, 9, 2, 7], 12
    eng = ServingEngine(cfg, params, **kw)
    rid = eng.submit(prompt, max_new)
    ref = eng.run()[rid]
    g = 7                                  # 4 + 7 = 11 > bucket 8
    eng2 = ServingEngine(cfg, params, **kw)
    with pytest.raises(ValueError, match="bucket"):
        eng2.validate_request(ref[:len(prompt) + g], max_new - g)
    rid2 = eng2.submit(ref[:len(prompt) + g], max_new - g,
                       resume_from=g)
    assert eng2.run()[rid2] == ref


def test_engine_rejects_bad_resume_from(llama_tiny):
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg, params = llama_tiny
    eng = ServingEngine(cfg, params, **_engine_kw(False))
    with pytest.raises(ValueError, match="resume_from"):
        eng.validate_request([1, 2, 3], 4, None, 3)
    with pytest.raises(ValueError, match="resume_from"):
        eng.validate_request([1, 2, 3], 4, None, -1)


@pytest.mark.parametrize("sampling", [False, True],
                         ids=["greedy", "seeded-sampling"])
def test_chaos_failover_parity_real_engine(llama_tiny, sampling):
    """THE acceptance contract: a deterministic fault plan kills one
    of two replicas mid-decode under concurrent load; every accepted
    request completes and its full token stream equals the
    uninterrupted single-replica run."""
    import numpy as np

    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg, params = llama_tiny
    kw = _engine_kw(sampling)
    rng = np.random.default_rng(0)
    reqs = [([int(t) for t in rng.integers(1, 200,
                                           int(rng.integers(2, 8)))],
             int(rng.integers(6, 14)), 1000 + i) for i in range(6)]

    ref_eng = ServingEngine(cfg, params, **kw)
    rids = [ref_eng.submit(p, m, seed=s if sampling else None)
            for p, m, s in reqs]
    ref_out = ref_eng.run()
    refs = [ref_out[r] for r in rids]

    engines = [ServingEngine(cfg, params, **kw) for _ in range(2)]
    for e in engines:       # prewarm: a first dispatch compiles, and
        e.submit([1, 2, 3], 5, seed=0 if sampling else None)
        e.run()             # the watchdog must not mistake XLA for a hang
    faults.arm("serve:dispatch:3:kill9:replica=0")
    gw = ServingGateway(engines, host="127.0.0.1", port=0,
                        max_queue=32, watchdog_timeout_s=10.0).start()
    try:
        results = [None] * len(reqs)

        def client(i):
            p, m, s = reqs[i]
            body = {"prompt": p, "max_new": m}
            if sampling:
                body["seed"] = s
            results[i] = _post(gw.port, body)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for (p, m, s), ref, (status, obj, _) in zip(reqs, refs,
                                                    results):
            assert status == 200, (status, obj)
            assert obj["tokens"] == ref
        assert gw.metrics.failovers.value() >= 1
        assert sum(r["state"] == "dead"
                   for r in gw.pool.replica_states()) == 1
    finally:
        faults.disarm()
        gw.drain(timeout=30)


def test_no_failover_kill_switch_restores_single_engine(llama_tiny,
                                                        monkeypatch):
    """TTD_NO_FAILOVER=1 with a multi-engine list drives only the
    first engine through the plain EngineDriver — outputs and the
    /healthz shape are byte-for-byte the single-engine gateway's."""
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg, params = llama_tiny
    kw = _engine_kw(False)

    single = ServingGateway(ServingEngine(cfg, params, **kw),
                            host="127.0.0.1", port=0).start()
    try:
        st, single_obj, _ = _post(single.port, {"prompt": [1, 2, 3],
                                                "max_new": 6})
        assert st == 200
        single_health = json.loads(_get(single.port, "/healthz")[1])
    finally:
        single.drain(timeout=20)

    monkeypatch.setenv("TTD_NO_FAILOVER", "1")
    gw = ServingGateway([ServingEngine(cfg, params, **kw),
                         ServingEngine(cfg, params, **kw)],
                        host="127.0.0.1", port=0).start()
    try:
        assert gw.pool is None
        from tensorflow_train_distributed_tpu.server.driver import (
            EngineDriver,
        )

        assert isinstance(gw.driver, EngineDriver)
        st, obj, _ = _post(gw.port, {"prompt": [1, 2, 3],
                                     "max_new": 6})
        assert st == 200
        assert obj["tokens"] == single_obj["tokens"]
        health = json.loads(_get(gw.port, "/healthz")[1])
        assert set(health) == set(single_health)
        assert "replicas" not in health
    finally:
        gw.drain(timeout=20)


# ── serving chaos smoke (tools/chaos_check.py --serving) ───────────────


def test_chaos_check_serving_smoke():
    """Tier-1-sized smoke of the serving chaos gate: the greedy leg of
    ``tools/chaos_check.py --serving`` run in-process (the CLI runs
    both legs; the sampled leg's parity is pinned above)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        from chaos_check import run_serving_chaos
    finally:
        sys.path.pop(0)

    verdict = run_serving_chaos(sampling=False, n_requests=4)
    assert verdict["ok"], verdict
    assert verdict["checks"]["streams_match_reference"]
    assert verdict["checks"]["one_replica_dead"]
