"""Profiling/observability tests: trace capture, step windows, memory stats."""

import glob
import os

import pytest

from tensorflow_train_distributed_tpu.runtime import profiling


def test_trace_writes_xplane(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    with profiling.trace(logdir):
        with profiling.annotate("unit-test-span"):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    # XPlane capture lands under plugins/profile/<run>/ as .xplane.pb.
    found = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert found, f"no xplane produced under {logdir}"


def test_profile_callback_window(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(profiling, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(profiling, "stop_trace",
                        lambda: calls.append(("stop", None)))
    cb = profiling.ProfileCallback(str(tmp_path), start_step=3, stop_step=5)
    for step in range(1, 8):
        cb.on_step_end(step, {})
    cb.on_train_end(None)
    assert [c[0] for c in calls] == ["start", "stop"]


def test_profile_callback_stops_at_train_end(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(profiling, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(profiling, "stop_trace",
                        lambda: calls.append("stop"))
    cb = profiling.ProfileCallback(str(tmp_path), start_step=1, stop_step=99)
    cb.on_step_end(1, {})
    cb.on_train_end(None)
    assert calls == ["start", "stop"]


def test_profile_callback_validates_window(tmp_path):
    with pytest.raises(ValueError):
        profiling.ProfileCallback(str(tmp_path), start_step=5, stop_step=3)


def test_device_memory_stats_enumerates_devices():
    import jax

    stats = profiling.device_memory_stats()
    assert len(stats) == len(jax.local_devices())
    assert all("device" in s for s in stats)


def test_speed_monitor_summary():
    import time

    mon = profiling.SpeedMonitor(examples_per_step=64)
    # Simulate fit's drain pattern: bursts of step reports per log window.
    for window in range(4):
        for step in (2 * window + 1, 2 * window + 2):
            mon.on_step_end(step, {})
        time.sleep(0.01)
    s = mon.summary()
    # 2 steps per ~10ms window → ~5 ms/step, never the µs intra-burst gap.
    assert 2.0 < s["median_step_ms"] < 50.0, s
    assert "examples_per_sec" in s


def test_speed_monitor_ignores_intra_burst_deltas():
    mon = profiling.SpeedMonitor()
    for step in range(1, 11):  # one burst, no wall time between steps
        mon.on_step_end(step, {})
    assert mon.summary() == {}  # no closed window yet → no bogus samples


class TestStallWatchdog:
    def test_fires_on_stall_and_quiet_when_stepping(self, capsys):
        import time

        from tensorflow_train_distributed_tpu.training import StallWatchdog

        wd = StallWatchdog(timeout_s=0.3)
        wd.on_train_begin(None)
        try:
            # Stepping regularly: never fires.
            for i in range(4):
                time.sleep(0.1)
                wd.on_step_end(i, {})
            assert wd.stall_count == 0
            # Silence past the timeout: fires (and re-arms, no spam).
            time.sleep(0.6)
            assert wd.stall_count >= 1
        finally:
            wd.on_train_end(None)
        assert not wd._thread.is_alive()

    def test_rejects_bad_timeout(self):
        import pytest as _pytest

        from tensorflow_train_distributed_tpu.training import StallWatchdog

        with _pytest.raises(ValueError, match="timeout_s"):
            StallWatchdog(timeout_s=0)

    def test_cli_flag_installs_watchdog(self):
        from tensorflow_train_distributed_tpu import launch

        result = launch.run(launch.build_parser().parse_args([
            "--config", "mnist", "--steps", "2", "--platform", "cpu",
            "--stall-timeout", "600",
        ]))
        import numpy as np

        assert np.isfinite(result.history["loss"][-1])


def test_profiler_server_starts_and_stops():
    import socket

    import jax

    from tensorflow_train_distributed_tpu.runtime.profiling import (
        start_profiler_server,
    )

    # A fixed port collides across concurrent CI runs; grab a free one.
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    start_profiler_server(port=port)
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5):
            pass  # something is listening
    finally:
        jax.profiler.stop_server()


def test_watchdog_stops_when_fit_raises(mesh8):
    """fit must run train_end (stopping the watchdog thread) even when a
    step raises — otherwise the daemon dumps stacks forever after."""
    import optax

    from tensorflow_train_distributed_tpu.training import (
        StallWatchdog, Trainer, TrainerConfig,
    )
    from tests.test_trainer import _BlobsTask, _loader

    wd = StallWatchdog(timeout_s=60)
    trainer = Trainer(_BlobsTask(), optax.adam(1e-2), mesh8,
                      config=TrainerConfig(log_every=1), callbacks=[wd])

    def exploding():
        yield next(iter(_loader()))
        raise RuntimeError("input pipeline died")

    with pytest.raises(RuntimeError, match="input pipeline died"):
        trainer.fit(exploding(), steps=10)
    assert wd._stop is None or wd._stop.is_set()
    assert not wd._thread.is_alive()


def test_watchdog_paused_during_eval():
    import time

    from tensorflow_train_distributed_tpu.training import StallWatchdog

    wd = StallWatchdog(timeout_s=0.2)
    wd.on_train_begin(None)
    try:
        wd.on_eval_begin()
        time.sleep(0.7)          # long eval window: must NOT count
        assert wd.stall_count == 0
        wd.on_eval_end()
        time.sleep(0.1)
        assert wd.stall_count == 0
    finally:
        wd.on_train_end(None)
