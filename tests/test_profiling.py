"""Profiling/observability tests: trace capture, step windows, memory stats."""

import glob
import os

import pytest

from tensorflow_train_distributed_tpu.runtime import profiling


def test_trace_writes_xplane(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    with profiling.trace(logdir):
        with profiling.annotate("unit-test-span"):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    # XPlane capture lands under plugins/profile/<run>/ as .xplane.pb.
    found = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert found, f"no xplane produced under {logdir}"


def test_profile_callback_window(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(profiling, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(profiling, "stop_trace",
                        lambda: calls.append(("stop", None)))
    cb = profiling.ProfileCallback(str(tmp_path), start_step=3, stop_step=5)
    for step in range(1, 8):
        cb.on_step_end(step, {})
    cb.on_train_end(None)
    assert [c[0] for c in calls] == ["start", "stop"]


def test_profile_callback_stops_at_train_end(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(profiling, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(profiling, "stop_trace",
                        lambda: calls.append("stop"))
    cb = profiling.ProfileCallback(str(tmp_path), start_step=1, stop_step=99)
    cb.on_step_end(1, {})
    cb.on_train_end(None)
    assert calls == ["start", "stop"]


def test_profile_callback_validates_window(tmp_path):
    with pytest.raises(ValueError):
        profiling.ProfileCallback(str(tmp_path), start_step=5, stop_step=3)


def test_device_memory_stats_enumerates_devices():
    import jax

    stats = profiling.device_memory_stats()
    assert len(stats) == len(jax.local_devices())
    assert all("device" in s for s in stats)


def test_speed_monitor_summary():
    import time

    mon = profiling.SpeedMonitor(examples_per_step=64)
    # Simulate fit's drain pattern: bursts of step reports per log window.
    for window in range(4):
        for step in (2 * window + 1, 2 * window + 2):
            mon.on_step_end(step, {})
        time.sleep(0.01)
    s = mon.summary()
    # 2 steps per ~10ms window → ~5 ms/step, never the µs intra-burst gap.
    assert 2.0 < s["median_step_ms"] < 50.0, s
    assert "examples_per_sec" in s


def test_speed_monitor_ignores_intra_burst_deltas():
    mon = profiling.SpeedMonitor()
    for step in range(1, 11):  # one burst, no wall time between steps
        mon.on_step_end(step, {})
    assert mon.summary() == {}  # no closed window yet → no bogus samples
