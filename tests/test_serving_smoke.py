"""Fast-tier smoke for the continuous-batching engine.

The full parity/contention/sampling matrix lives in test_serving.py
(slow tier); this keeps ONE end-to-end engine run in the fast CI tier
so a broken import, cache-shape regression, or host-loop bug is caught
within minutes, not only on the full run.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_train_distributed_tpu.models.llama import (
    LLAMA_PRESETS,
    LlamaModel,
)
from tensorflow_train_distributed_tpu.serving import ServingEngine


def test_engine_smoke():
    cfg = LLAMA_PRESETS["llama_tiny"]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]
    eng = ServingEngine(cfg, params, slots=2, cache_len=16, chunk=2,
                        prompt_buckets=(8,))
    rid_a = eng.submit([1, 2, 3], 4)
    rid_b = eng.submit([4, 5], 3)
    out = eng.run()
    assert out[rid_a][:3] == [1, 2, 3] and len(out[rid_a]) == 7
    assert out[rid_b][:2] == [4, 5] and len(out[rid_b]) == 5
    vocab = cfg.vocab_size
    assert all(0 <= t < vocab for r in out.values() for t in r)
    assert all(np.issubdtype(type(t), np.integer) or isinstance(t, int)
               for r in out.values() for t in r)
