"""Model zoo tests: every reference config family trains on the CPU mesh.

Tiny variants exercise the full code path (attention, BN, scan, remat);
param-count checks pin the full-size architectures without compiling them.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tensorflow_train_distributed_tpu.data import (
    DataConfig, HostDataLoader, get_dataset,
)
from tensorflow_train_distributed_tpu.models import registry
from tensorflow_train_distributed_tpu.models.bert import BERT_PRESETS, BertEncoder
from tensorflow_train_distributed_tpu.models.lenet import LeNet
from tensorflow_train_distributed_tpu.models.llama import (
    LLAMA_PRESETS, LlamaModel,
)
from tensorflow_train_distributed_tpu.models.resnet import (
    RESNET_PRESETS, ResNet,
)
from tensorflow_train_distributed_tpu.models.transformer import (
    TRANSFORMER_PRESETS, Seq2SeqTransformer,
)
from tensorflow_train_distributed_tpu.training import Trainer, TrainerConfig
from tensorflow_train_distributed_tpu.training.callbacks import History


def _param_count(model, *args):
    shapes = jax.eval_shape(lambda: model.init(jax.random.key(0), *args))
    return sum(np.prod(x.shape) for x in jax.tree.leaves(shapes))


class TestArchitectures:
    def test_lenet_param_count(self):
        # Classic LeNet-5 on 28x28: 61,706 params.
        n = _param_count(LeNet(), jnp.zeros((1, 28, 28, 1)))
        assert n == 61_706

    def test_resnet50_param_count(self):
        n = _param_count(ResNet(RESNET_PRESETS["resnet50"]),
                         jnp.zeros((1, 224, 224, 3)))
        assert abs(n - 25.56e6) < 0.1e6, n  # ResNet-50: ~25.56M

    def test_bert_base_param_count(self):
        n = _param_count(BertEncoder(BERT_PRESETS["bert_base"]),
                         jnp.zeros((1, 16), jnp.int32))
        assert abs(n - 110e6) < 3e6, n  # BERT-base: ~110M

    def test_transformer_big_param_count(self):
        n = _param_count(
            Seq2SeqTransformer(TRANSFORMER_PRESETS["transformer_big"]),
            jnp.zeros((1, 8), jnp.int32), jnp.zeros((1, 8), jnp.int32))
        assert abs(n - 210e6) < 15e6, n  # Transformer-big: ~210M

    def test_llama2_7b_param_count(self):
        n = _param_count(LlamaModel(LLAMA_PRESETS["llama2_7b"]),
                         jnp.zeros((1, 8), jnp.int32))
        assert abs(n - 6.74e9) < 0.1e9, n  # Llama-2-7B: 6.74B

    def test_qwen_presets_carry_checkpoint_norm_epsilon(self):
        """Qwen checkpoints use rms_norm_eps=1e-6; a preset left at the
        family default 1e-5 imports into silently-different logits on
        the config=task_cfg CLI route (ADVICE round 5)."""
        from tensorflow_train_distributed_tpu.models.moe import (
            MOE_PRESETS,
        )

        assert LLAMA_PRESETS["qwen25_7b"].rms_epsilon == 1e-6
        assert MOE_PRESETS["qwen15_moe_a27b"].rms_epsilon == 1e-6

    def test_llama_scan_matches_loop_params(self):
        loop_cfg = LLAMA_PRESETS["llama_tiny"]
        scan_cfg = LLAMA_PRESETS["llama_tiny_scan"]
        n_loop = _param_count(LlamaModel(loop_cfg),
                              jnp.zeros((1, 8), jnp.int32))
        n_scan = _param_count(LlamaModel(scan_cfg),
                              jnp.zeros((1, 8), jnp.int32))
        assert n_loop == n_scan

    @pytest.mark.parametrize("policy", ["dots", "no_ffn"])
    def test_llama_remat_policy_matches_full(self, policy):
        """'dots'/'no_ffn' remat save more, recompute less — same math:
        loss AND gradients must match full remat exactly."""
        import dataclasses

        import jax
        import numpy as np

        from tensorflow_train_distributed_tpu.models.llama import (
            CausalLmTask,
        )

        rng = np.random.default_rng(0)
        batch = {
            "tokens": rng.integers(0, 256, (2, 32)).astype(np.int32),
            "targets": rng.integers(0, 256, (2, 32)).astype(np.int32),
        }

        def loss_and_grad(pol):
            cfg = dataclasses.replace(LLAMA_PRESETS["llama_tiny_scan"],
                                      remat_policy=pol)
            task = CausalLmTask(cfg)
            variables = task.init_variables(jax.random.key(0), batch)

            def loss(params):
                value, _ = task.loss_fn(params, {}, batch,
                                        jax.random.key(1), True)
                return value

            return jax.value_and_grad(loss)(variables["params"])

        (l_full, g_full) = loss_and_grad("full")
        (l_p, g_p) = loss_and_grad(policy)
        np.testing.assert_allclose(float(l_full), float(l_p), rtol=1e-6)
        # Gradients: recompute-vs-saved changes f32 reassociation, so
        # element-wise rounding differs; bound the relative tree error.
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=5e-3, atol=1e-5),
            g_full, g_p)

    def test_llama_remat_policy_unknown_rejected(self):
        import dataclasses

        import pytest as _pytest

        from tensorflow_train_distributed_tpu.models.llama import (
            _checkpoint_policy,
        )

        cfg = dataclasses.replace(LLAMA_PRESETS["llama_tiny_scan"],
                                  remat_policy="nope")
        with _pytest.raises(ValueError, match="remat_policy"):
            _checkpoint_policy(cfg)


def _train_config(name, steps=12, mesh=None, **overrides):
    entry = registry.get_entry(name)
    entry.update(overrides)
    ds = get_dataset(entry["dataset"], num_examples=256,
                     **entry["dataset_kwargs"])
    loader = HostDataLoader(
        ds, DataConfig(global_batch_size=entry["global_batch_size"]))
    trainer = Trainer(
        entry["task_factory"](),
        optax.adam(entry["learning_rate"]),
        mesh,
        config=TrainerConfig(log_every=4),
        callbacks=[hist := History()],
    )
    state = trainer.fit(iter(loader), steps=steps)
    return state, hist


@pytest.mark.slow  # full fit loops per config family
class TestTraining:
    def test_mnist_lenet_converges(self, mesh8):
        state, hist = _train_config("mnist", steps=30, mesh=mesh8,
                                    global_batch_size=64)
        assert hist.history["loss"][-1] < hist.history["loss"][0] * 0.5
        assert hist.history["accuracy"][-1] > 0.5

    def test_resnet_tiny_trains_with_bn(self, mesh8):
        state, hist = _train_config("resnet_tiny", steps=8, mesh=mesh8,
                                    global_batch_size=16)
        # batch_stats updated (BN running means move off zero).
        bn_means = [np.asarray(x) for path, x in
                    jax.tree_util.tree_leaves_with_path(
                        state.model_state["batch_stats"])
                    if path[-1].key == "mean"]
        assert any(np.abs(m).max() > 0 for m in bn_means)
        assert hist.history["loss"][-1] < hist.history["loss"][0]

    def test_resnet_space_to_depth_equivalence(self):
        """The s2d stem is the SAME function: transforming a trained 7x7
        stem kernel with stem_kernel_to_s2d and feeding s2d input must
        reproduce the baseline logits exactly (MLPerf s2d trick)."""
        import dataclasses

        import flax.linen as nn
        import jax.numpy as jnp

        from tensorflow_train_distributed_tpu.models import resnet

        cfg = dataclasses.replace(resnet.RESNET_PRESETS["resnet_tiny"],
                                  space_to_depth=False)
        cfg_s2d = dataclasses.replace(cfg, space_to_depth=True)
        model, model_s2d = resnet.ResNet(cfg), resnet.ResNet(cfg_s2d)
        x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3),
                              jnp.float32)
        variables = nn.unbox(model.init(jax.random.key(1), x, train=False))
        params = variables["params"]
        params_s2d = jax.tree.map(lambda p: p, params)
        params_s2d["stem_conv"] = {
            "kernel": resnet.stem_kernel_to_s2d(
                params["stem_conv"]["kernel"])
        }
        ref = model.apply({"params": params, **{
            k: v for k, v in variables.items() if k != "params"}}, x,
            train=False)
        out = model_s2d.apply({"params": params_s2d, **{
            k: v for k, v in variables.items() if k != "params"}},
            resnet.space_to_depth(x), train=False)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=1e-5)

    def test_resnet_s2d_dataset_layout_matches_model(self):
        """Host-side dataset s2d must equal the model's on-the-fly s2d."""
        from tensorflow_train_distributed_tpu.data.datasets import (
            SyntheticImageNet,
        )
        from tensorflow_train_distributed_tpu.models import resnet

        raw = SyntheticImageNet(num_examples=4, image_size=32, seed=3)
        s2d = SyntheticImageNet(num_examples=4, image_size=32, seed=3,
                                space_to_depth=True)
        img = raw[1]["image"][None]
        np.testing.assert_array_equal(
            np.asarray(resnet.space_to_depth(img))[0], s2d[1]["image"])

    def test_bert_tiny_mlm_trains(self, mesh8):
        state, hist = _train_config("bert_tiny_mlm", steps=12, mesh=mesh8)
        assert hist.history["loss"][-1] < hist.history["loss"][0]
        assert "mlm_accuracy" in hist.history

    def test_bert_mlm_val_metrics_drive_early_stopping(self, mesh8):
        """BERT MLM eval parity: held-out val_loss + val_mlm_accuracy flow
        through fit's eval loop and drive EarlyStopping — the [SPEC]
        'samples/sec + loss match' metric pair, closed end-to-end."""
        import optax

        from tensorflow_train_distributed_tpu.data import (
            DataConfig, HostDataLoader, get_dataset, train_val_split,
        )
        from tensorflow_train_distributed_tpu.models import bert
        from tensorflow_train_distributed_tpu.training import (
            EarlyStopping, History, Trainer, TrainerConfig,
        )

        src = get_dataset("mlm", num_examples=512, vocab_size=256,
                          seq_len=64)
        train_src, val_src = train_val_split(src, 0.25)
        loader = HostDataLoader(
            train_src, DataConfig(global_batch_size=32, seed=0))
        # min_delta=0.5: only the initial steep descent counts as
        # improvement, so the stop fires deterministically mid-run.
        es = EarlyStopping(monitor="val_loss", patience=2, min_delta=0.5)
        trainer = Trainer(
            bert.make_task(bert.BERT_PRESETS["bert_tiny"]),
            optax.adam(2e-3), mesh8,
            config=TrainerConfig(log_every=5),
            callbacks=[hist := History(), es])
        state = trainer.fit(
            loader, steps=300,
            eval_batches=lambda: HostDataLoader(
                val_src, DataConfig(global_batch_size=32, seed=1,
                                    num_epochs=1)),
            eval_every=10, eval_steps=4)
        assert "val_loss" in hist.history
        assert "val_mlm_accuracy" in hist.history
        # Learned on the held-out split (the stop fires only after the
        # steep descent, so the total drop exceeds min_delta).
        assert (hist.history["val_loss"][-1]
                < hist.history["val_loss"][0] - 0.5)
        # EarlyStopping actually stopped the run on the val_loss plateau,
        # and its best tracked the qualifying (>min_delta) improvements.
        assert int(state.step) < 300
        assert es.best < hist.history["val_loss"][0] - 0.5

    def test_transformer_tiny_wmt_trains(self, mesh8):
        state, hist = _train_config("transformer_tiny_wmt", steps=12,
                                    mesh=mesh8)
        assert hist.history["loss"][-1] < hist.history["loss"][0]

    def test_llama_tiny_trains_2d_mesh(self, mesh_2d):
        state, hist = _train_config("llama_tiny_sft", steps=12, mesh=mesh_2d)
        assert hist.history["loss"][-1] < hist.history["loss"][0]

    def test_llama_scan_remat_trains_and_shards(self, mesh_2d):
        from tensorflow_train_distributed_tpu.models import llama

        entry = registry.get_entry("llama_tiny_sft")
        ds = get_dataset("lm", num_examples=64, vocab_size=256, seq_len=32)
        loader = HostDataLoader(ds, DataConfig(global_batch_size=16))
        task = llama.make_task(llama.LLAMA_PRESETS["llama_tiny_scan"])
        trainer = Trainer(task, optax.adam(1e-3), mesh_2d,
                          config=TrainerConfig(log_every=4),
                          callbacks=[hist := History()])
        state = trainer.fit(iter(loader), steps=8)
        # Scanned stack: params carry leading layer axis.
        stack = state.params["layers"]["stack"]["block"]
        gate = stack["mlp"]["wi_gate"]["kernel"]
        assert gate.shape[0] == 2  # num_layers
        # mlp dim sharded over tensor axis on the 2x4 mesh.
        assert gate.addressable_shards[0].data.shape[-1] == gate.shape[-1] // 4
        assert hist.history["loss"][-1] < hist.history["loss"][0]


class TestLlama7bMemoryBudget:
    """SURVEY §7 calls the 7B memory layout make-or-break; validate it AOT
    (eval_shape + sharding arithmetic, no chips) against the v5e 16-GiB
    HBM budget."""

    V5E_HBM = 16 * 2**30

    def _plan(self, mesh):
        import numpy as np

        from tensorflow_train_distributed_tpu.models import llama
        from tensorflow_train_distributed_tpu.training import (
            plan_state_memory,
        )

        task = llama.make_task(llama.LLAMA_PRESETS["llama2_7b"])
        batch = {"tokens": np.zeros((8, 4096), np.int32),
                 "targets": np.zeros((8, 4096), np.int32)}
        return plan_state_memory(task, batch, optax.adamw(1e-5), mesh)

    def test_fsdp_tp_fits_v5e8_and_v5e16(self):
        from tensorflow_train_distributed_tpu.runtime.compat import (
            abstract_mesh,
        )

        from tensorflow_train_distributed_tpu.runtime.mesh import (
            AXES, MeshConfig, build_mesh,
        )

        plan8 = self._plan(build_mesh(MeshConfig(data=1, fsdp=2, tensor=4)))
        # ~26 GB params+opt (7B × 12 bytes: f32 master + adam mu/nu),
        # sharded 8-ways with a small replicated floor (norm scales).
        assert plan8["total_bytes"] > 70 * 2**30
        assert plan8["per_device_bytes"] < self.V5E_HBM
        assert plan8["replicated_bytes"] < 2**30
        # v5e-16 (fsdp=4 × tensor=4) — AbstractMesh: no 16 devices needed.
        sizes = dict.fromkeys(AXES, 1)
        sizes.update(fsdp=4, tensor=4)
        mesh16 = abstract_mesh(tuple(sizes[a] for a in AXES), AXES)
        plan16 = self._plan(mesh16)
        assert plan16["per_device_bytes"] < self.V5E_HBM / 2
        assert plan16["per_device_bytes"] < plan8["per_device_bytes"]

    def test_dp_tp_exceeds_v5e_documenting_fsdp_default(self):
        """Pure dp×tp replicates params+opt over data: 7B with adam needs
        ~19 GiB/device at tensor=4 regardless of the data size — that is
        WHY the llama2_7b_sft registry config defaults to fsdp_tp."""
        from tensorflow_train_distributed_tpu.models import registry
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )

        plan = self._plan(build_mesh(MeshConfig(data=2, tensor=4)))
        assert plan["per_device_bytes"] > self.V5E_HBM
        assert registry.get_entry("llama2_7b_sft")["strategy"] == "fsdp_tp"


class TestActivationMemoryModel:
    """training.memory: the calibrated activation estimate — pinned to the
    three OOM points measured on the real v5e chip (PROFILE.md)."""

    V5E_BUDGET = 15.75 * 2**30

    def _estimate(self, preset, batch, seq, remat):
        from tensorflow_train_distributed_tpu.models import llama
        from tensorflow_train_distributed_tpu.training.memory import (
            STATE_BYTES_PER_PARAM, decoder_activation_bytes,
        )

        cfg = llama.LLAMA_PRESETS[preset]
        model = llama.LlamaModel(cfg)
        import numpy as np

        abstract = jax.eval_shape(
            lambda: model.init(jax.random.key(0),
                               np.zeros((1, seq), np.int32)))
        n_params = sum(
            x.size for x in jax.tree_util.tree_leaves(abstract["params"]))
        state = n_params * STATE_BYTES_PER_PARAM
        act = decoder_activation_bytes(
            cfg.num_layers, cfg.d_model, batch, seq, remat=remat)
        return state + act

    def test_measured_point_125m_b8_noremat_fits(self):
        # Measured: runs at 31.8k tok/s on the chip.
        est = self._estimate("llama_125m", 8, 2048, remat=False)
        assert est <= self.V5E_BUDGET

    def test_measured_point_125m_b16_noremat_refused(self):
        # Measured: OOM, 26.4 GiB requested.  The estimate must refuse
        # the budget (that's the guard's job) and stay in the measured
        # point's calibration band — not so low it green-lights a tunnel
        # killer.
        est = self._estimate("llama_125m", 16, 2048, remat=False)
        assert est > self.V5E_BUDGET
        assert est > 0.7 * 26.4 * 2**30

    def test_no_ffn_policy_sits_between_remat_and_no_remat(self):
        from tensorflow_train_distributed_tpu.training.memory import (
            decoder_activation_bytes,
        )

        kw = dict(num_layers=12, d_model=768, batch=16, seq=2048)
        no_remat = decoder_activation_bytes(remat=False, **kw)
        no_ffn = decoder_activation_bytes(remat=False, ffn_size=2048,
                                          save_ffn_hiddens=False, **kw)
        remat = decoder_activation_bytes(remat=True, **kw)
        assert remat < no_ffn < no_remat

    def test_measured_point_1b_noremat_state_refused(self):
        # Measured: llama_1b state alone exceeds the chip.
        est = self._estimate("llama_1b", 16, 2048, remat=False)
        assert est > 17 * 2**30

    def test_plan_train_memory_7b_v5e16(self):
        """The combined planner: 7B fsdp4xtp4 fits v5e-16 at small batch
        with remat, and refuses the large-batch config."""
        import numpy as np
        import optax

        from tensorflow_train_distributed_tpu.runtime.compat import (
            abstract_mesh,
        )

        from tensorflow_train_distributed_tpu.models import llama
        from tensorflow_train_distributed_tpu.runtime.mesh import AXES
        from tensorflow_train_distributed_tpu.training import (
            plan_train_memory,
        )

        sizes = dict.fromkeys(AXES, 1)
        sizes.update(fsdp=4, tensor=4)
        mesh16 = abstract_mesh(tuple(sizes[a] for a in AXES), AXES)
        task = llama.make_task(llama.LLAMA_PRESETS["llama2_7b"])

        def plan(batch):
            b = {"tokens": np.zeros((batch, 4096), np.int32),
                 "targets": np.zeros((batch, 4096), np.int32)}
            return plan_train_memory(task, b, optax.adamw(1e-5), mesh16,
                                     device_kind="TPU v5e")

        small = plan(4)
        assert small["fits"], small
        assert small["activation_bytes_per_device"] > 0
        big = plan(64)
        assert not big["fits"], big
        assert (big["step_bytes_per_device"]
                > small["step_bytes_per_device"])


@pytest.mark.slow  # full 7B SPMD compile
class TestLlama7bAotCompile:
    """Compile-level 7B proof (VERDICT r2 item 5): the REAL llama2_7b
    train step AOT-lowers and runs the full XLA SPMD partitioning
    pipeline over an fsdp x tp mesh with nothing materialized — the
    collective structure is asserted from the compiled HLO."""

    def test_7b_partitions_on_8dev_fsdp_tp(self, mesh8):
        import numpy as np
        import optax

        from tensorflow_train_distributed_tpu.models import llama
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )
        from tensorflow_train_distributed_tpu.training import (
            Policy, Trainer, TrainerConfig,
        )

        mesh = build_mesh(MeshConfig(fsdp=2, tensor=4))
        task = llama.CausalLmTask(llama.LLAMA_PRESETS["llama2_7b"])
        trainer = Trainer(
            task, optax.adamw(1e-4, b1=0.9, b2=0.95, weight_decay=0.1),
            mesh, policy=Policy.from_name("mixed_bfloat16"),
            config=TrainerConfig(log_every=1_000_000))
        batch = {"tokens": np.zeros((8, 4096), np.int32),
                 "targets": np.zeros((8, 4096), np.int32)}
        compiled = trainer.lower_train_step(batch).compile()
        txt = compiled.as_text()
        # fsdp: params all-gather before use; grads reduced across fsdp.
        # tp: activation all-reduce (Megatron row/col pattern).
        assert txt.count("all-gather") > 0
        assert txt.count("all-reduce") > 0
        # State never materializes unsharded: per-device argument bytes
        # are ~1/8 of the ~84 GB f32+moments state.
        mem = compiled.memory_analysis()
        assert mem.argument_size_in_bytes < 15 * 2**30


class TestRegistry:
    def test_all_reference_configs_present(self):
        names = registry.available()
        # The five reference configs (BASELINE.json) all have entries.
        for required in ("mnist", "resnet50_imagenet", "bert_base_mlm",
                         "transformer_big_wmt", "llama2_7b_sft"):
            assert required in names, required

    def test_unknown_config_raises(self):
        with pytest.raises(ValueError, match="Unknown config"):
            registry.get_entry("alexnet")


class TestEncoderRemat:
    """remat=True is a pure memory/speed trade: params, forward, and
    grads must be bit-identical (nn.remat is a transparent lift, so
    trained/HF checkpoints load unchanged)."""

    def test_bert_remat_parity(self):
        import dataclasses

        from tensorflow_train_distributed_tpu.models import bert

        cfg0 = bert.BERT_PRESETS["bert_tiny"]
        cfg1 = dataclasses.replace(cfg0, remat=True)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg0.vocab_size, (2, 16)).astype(np.int32)
        p0 = bert.BertEncoder(cfg0).init(jax.random.key(0), ids)["params"]
        p1 = bert.BertEncoder(cfg1).init(jax.random.key(0), ids)["params"]
        assert (jax.tree_util.tree_structure(p0)
                == jax.tree_util.tree_structure(p1))
        o0 = bert.BertEncoder(cfg0).apply({"params": p0}, ids)
        o1 = bert.BertEncoder(cfg1).apply({"params": p0}, ids)
        np.testing.assert_allclose(np.asarray(o0), np.asarray(o1),
                                   atol=1e-6)
        g = lambda cfg: jax.grad(  # noqa: E731
            lambda p: bert.BertEncoder(cfg).apply(
                {"params": p}, ids).sum())(p0)
        # rtol, not just atol: remat recompute reorders float32 sums, so
        # gradients of magnitude ~1e2 carry ~1e-4 absolute noise on some
        # XLA versions; a real parity break would be O(1) relative.
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4),
            g(cfg0), g(cfg1))

    def test_transformer_remat_parity(self):
        import dataclasses

        from tensorflow_train_distributed_tpu.models import transformer

        cfg0 = transformer.TRANSFORMER_PRESETS["transformer_tiny"]
        cfg1 = dataclasses.replace(cfg0, remat=True)
        rng = np.random.default_rng(1)
        src = rng.integers(0, cfg0.vocab_size, (2, 8)).astype(np.int32)
        M = transformer.Seq2SeqTransformer
        p0 = M(cfg0).init(jax.random.key(1), src, src)["params"]
        p1 = M(cfg1).init(jax.random.key(1), src, src)["params"]
        assert (jax.tree_util.tree_structure(p0)
                == jax.tree_util.tree_structure(p1))
        o0 = M(cfg0).apply({"params": p0}, src, src)
        o1 = M(cfg1).apply({"params": p0}, src, src)
        np.testing.assert_allclose(np.asarray(o0), np.asarray(o1),
                                   atol=1e-5)
        g = lambda cfg: jax.grad(  # noqa: E731
            lambda p: M(cfg).apply({"params": p}, src, src)
            .astype(jnp.float32).sum())(p0)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4),
            g(cfg0), g(cfg1))


@pytest.mark.slow  # fit loop
def test_vision_top5_metric(mesh8):
    """ImageNet convention: top-5 accuracy reported alongside top-1 (and
    top-5 >= top-1 by construction); LeNet/MNIST (10 classes) gets it,
    and it flows through fit's metric pipeline."""
    import optax

    from tensorflow_train_distributed_tpu.data import (
        DataConfig, HostDataLoader,
    )
    from tensorflow_train_distributed_tpu.data.datasets import get_dataset
    from tensorflow_train_distributed_tpu.models import lenet
    from tensorflow_train_distributed_tpu.training import (
        History, Trainer, TrainerConfig,
    )

    loader = HostDataLoader(get_dataset("mnist", num_examples=128),
                            DataConfig(global_batch_size=32))
    trainer = Trainer(lenet.make_task(), optax.adam(1e-3), mesh8,
                      config=TrainerConfig(log_every=1),
                      callbacks=[hist := History()])
    trainer.fit(iter(loader), steps=3)
    assert "top5_accuracy" in hist.history
    assert all(t5 >= t1 - 1e-6 for t1, t5 in
               zip(hist.history["accuracy"], hist.history["top5_accuracy"]))


@pytest.mark.slow  # forks a 16-device interpreter
def test_7b_partitions_on_16dev_v5e16_subprocess():
    """The exact v5e-16 topology (fsdp=4 x tp=4): needs 16 virtual
    devices, which the session-scoped 8-device conftest can't provide —
    fork a fresh interpreter (the multihost-test pattern)."""
    import subprocess
    import sys

    src = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 16)
import numpy as np, optax
from tensorflow_train_distributed_tpu.models import llama
from tensorflow_train_distributed_tpu.runtime.mesh import MeshConfig, build_mesh
from tensorflow_train_distributed_tpu.training import Policy, Trainer, TrainerConfig

mesh = build_mesh(MeshConfig(fsdp=4, tensor=4))
task = llama.CausalLmTask(llama.LLAMA_PRESETS["llama2_7b"])
tr = Trainer(task, optax.adamw(1e-4, b1=0.9, b2=0.95, weight_decay=0.1),
             mesh, policy=Policy.from_name("mixed_bfloat16"),
             config=TrainerConfig(log_every=1_000_000))
batch = {"tokens": np.zeros((16, 4096), np.int32),
         "targets": np.zeros((16, 4096), np.int32)}
compiled = tr.lower_train_step(batch).compile()
txt = compiled.as_text()
assert txt.count("all-gather") > 0 and txt.count("all-reduce") > 0
mem = compiled.memory_analysis()
# ~84 GB state over 16 devices: strictly sharded arguments.
assert mem.argument_size_in_bytes < 8 * 2**30, mem.argument_size_in_bytes
print("OK", txt.count("all-gather"), txt.count("all-reduce"),
      mem.argument_size_in_bytes)
"""
    out = subprocess.run([sys.executable, "-c", src], capture_output=True,
                         text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


def test_plan_train_memory_refuses_moe():
    """The activation model has no MoE dispatch/expert-buffer terms; a
    silent underestimate would green-light tunnel-killing compiles."""
    import optax

    from tensorflow_train_distributed_tpu.runtime.compat import (
        abstract_mesh,
    )

    from tensorflow_train_distributed_tpu.models import moe
    from tensorflow_train_distributed_tpu.runtime.mesh import AXES
    from tensorflow_train_distributed_tpu.training import plan_train_memory

    sizes = dict.fromkeys(AXES, 1)
    sizes.update(expert=4)
    mesh = abstract_mesh(tuple(sizes[a] for a in AXES), AXES)
    b = {"tokens": np.zeros((4, 128), np.int32),
         "targets": np.zeros((4, 128), np.int32)}
    with pytest.raises(ValueError, match="MoE"):
        plan_train_memory(moe.make_task(moe.MOE_PRESETS["moe_tiny"]), b,
                          optax.adamw(1e-5), mesh)


class TestSubsampledStatsBN:
    """The BN-traffic attack (PROFILE.md: BN statistics dominate the
    ResNet step): strided-stats BN must be exact at stride 1, use the
    subsampled statistics at stride 2, and interchange checkpoints with
    the exact-BN presets."""

    def _io(self, seed=0, shape=(4, 8, 8, 6)):
        rng = np.random.default_rng(seed)
        return rng.standard_normal(shape, dtype=np.float32) * 2.0 + 0.5

    def test_stride1_matches_flax_batchnorm(self):
        import flax.linen as nn

        from tensorflow_train_distributed_tpu.models.resnet import (
            SubsampledStatsBN,
        )

        x = jnp.asarray(self._io())
        ours = SubsampledStatsBN(use_running_average=False, momentum=0.9,
                                 epsilon=1e-5, dtype=jnp.float32,
                                 stats_stride=1)
        ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-5, dtype=jnp.float32)
        v_ours = ours.init(jax.random.key(0), x)
        v_ref = ref.init(jax.random.key(0), x)
        y_ours, m_ours = ours.apply(v_ours, x, mutable=["batch_stats"])
        y_ref, m_ref = ref.apply(v_ref, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_ours), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5),
            m_ours["batch_stats"], m_ref["batch_stats"])

    def test_stride2_uses_subsampled_statistics(self):
        from tensorflow_train_distributed_tpu.models.resnet import (
            SubsampledStatsBN,
        )

        x = jnp.asarray(self._io(1))
        bn = SubsampledStatsBN(use_running_average=False, momentum=0.0,
                               epsilon=0.0, dtype=jnp.float32,
                               stats_stride=2)
        v = bn.init(jax.random.key(0), x)
        y, mut = bn.apply(v, x, mutable=["batch_stats"])
        sub = np.asarray(x)[:, ::2, ::2, :].astype(np.float64)
        mean = sub.mean((0, 1, 2))
        var = (sub ** 2).mean((0, 1, 2)) - mean ** 2
        # momentum=0 → running stats ARE this batch's stats.
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["mean"]), mean, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["var"]), var, rtol=1e-3)
        # Normalize-apply uses those stats over the FULL tensor.
        want = (np.asarray(x) - mean) / np.sqrt(var)
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-3,
                                   atol=1e-4)

    def test_eval_uses_running_stats(self):
        from tensorflow_train_distributed_tpu.models.resnet import (
            SubsampledStatsBN,
        )

        x = jnp.asarray(self._io(2))
        bn = SubsampledStatsBN(use_running_average=True, momentum=0.9,
                               epsilon=1e-5, dtype=jnp.float32)
        v = bn.init(jax.random.key(0), x)
        y = bn.apply(v, x)  # fresh stats: mean 0, var 1 → near-identity
        np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                                   rtol=1e-4, atol=1e-4)

    def test_bnsub_preset_checkpoint_interchanges(self):
        import dataclasses

        from tensorflow_train_distributed_tpu.models import resnet

        cfg = dataclasses.replace(resnet.RESNET_PRESETS["resnet_tiny"])
        cfg_sub = dataclasses.replace(cfg, bn_stats_stride=2)
        x = jnp.zeros((1, 16, 16, 3))
        v = resnet.ResNet(cfg).init(jax.random.key(0), x, train=False)
        v_sub = resnet.ResNet(cfg_sub).init(jax.random.key(0), x,
                                            train=False)
        assert (jax.tree_util.tree_structure(v)
                == jax.tree_util.tree_structure(v_sub))
        # Exact-BN variables evaluate through the subsampled model.
        y = resnet.ResNet(cfg_sub).apply(v, x, train=False)
        assert np.isfinite(np.asarray(y)).all()

    @pytest.mark.slow
    def test_bnsub_resnet_trains(self, mesh8):
        import dataclasses

        import optax

        from tensorflow_train_distributed_tpu.data import (
            DataConfig, HostDataLoader, get_dataset,
        )
        from tensorflow_train_distributed_tpu.models import resnet

        cfg = dataclasses.replace(resnet.RESNET_PRESETS["resnet_tiny"],
                                  bn_stats_stride=2)
        loader = HostDataLoader(
            get_dataset("imagenet", num_examples=64, num_classes=10,
                        image_size=32),
            DataConfig(global_batch_size=16))
        trainer = Trainer(resnet.make_task(cfg, label_smoothing=0.0,
                                           weight_decay=0.0),
                          optax.adam(1e-3), mesh8,
                          config=TrainerConfig(log_every=4),
                          callbacks=[hist := History()])
        state = trainer.fit(iter(loader), steps=8)
        assert np.isfinite(hist.history["loss"]).all()
        means = [np.asarray(x) for path, x in
                 jax.tree_util.tree_leaves_with_path(
                     state.model_state["batch_stats"])
                 if path[-1].key == "mean"]
        assert any(np.abs(m).max() > 0 for m in means)


class TestLlama13bScale:
    """llama2_13b: partitions through the full SPMD pipeline, and the
    planner gives honest fit answers (v5e-16 at seq 4096 does NOT fit —
    shrink seq or grow the slice; that refusal is the feature)."""

    def _plan(self, seq, axes):
        from tensorflow_train_distributed_tpu.runtime.compat import (
            abstract_mesh,
        )

        from tensorflow_train_distributed_tpu.models import llama
        from tensorflow_train_distributed_tpu.runtime.mesh import AXES
        from tensorflow_train_distributed_tpu.training import (
            plan_train_memory,
        )

        sizes = dict.fromkeys(AXES, 1)
        sizes.update(axes)
        mesh = abstract_mesh(tuple(sizes[a] for a in AXES), AXES)
        task = llama.make_task(llama.LLAMA_PRESETS["llama2_13b"])
        b = {"tokens": np.zeros((4, seq), np.int32),
             "targets": np.zeros((4, seq), np.int32)}
        return plan_train_memory(task, b, optax.adamw(1e-5), mesh,
                                 device_kind="TPU v5e")

    def test_planner_refuses_v5e16_seq4096(self):
        plan = self._plan(4096, dict(fsdp=4, tensor=4))
        assert not plan["fits"]

    def test_planner_fits_v5e16_seq2048(self):
        plan = self._plan(2048, dict(fsdp=4, tensor=4))
        assert plan["fits"], plan

    def test_planner_fits_v5e32_seq4096(self):
        plan = self._plan(4096, dict(fsdp=8, tensor=4))
        assert plan["fits"], plan

    @pytest.mark.slow  # full 13B SPMD compile
    def test_13b_partitions_on_8dev_fsdp_tp(self):
        from tensorflow_train_distributed_tpu.models import llama
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )
        from tensorflow_train_distributed_tpu.training import (
            Policy, Trainer, TrainerConfig,
        )

        mesh = build_mesh(MeshConfig(fsdp=2, tensor=4))
        task = llama.CausalLmTask(llama.LLAMA_PRESETS["llama2_13b"])
        trainer = Trainer(
            task, optax.adamw(1e-4, b1=0.9, b2=0.95, weight_decay=0.1),
            mesh, policy=Policy.from_name("mixed_bfloat16"),
            config=TrainerConfig(log_every=1_000_000))
        batch = {"tokens": np.zeros((8, 4096), np.int32),
                 "targets": np.zeros((8, 4096), np.int32)}
        compiled = trainer.lower_train_step(batch).compile()
        txt = compiled.as_text()
        assert txt.count("all-gather") > 0 and txt.count("all-reduce") > 0
