"""tools/merge_tpu_results.py: hunter-results → persisted-record merge.

Pure host logic (no jax): the merge must enrich the record without
clobbering families it did not re-measure, recompute the resnet headline
by bench.py's best-of rule, and stamp per-entry honesty timestamps.
"""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from merge_tpu_results import merge  # noqa: E402

BASE = {
    "metric": "resnet50_train_images_per_sec_per_chip",
    "value": 2433.7, "unit": "images/sec/chip", "vs_baseline": 0.973,
    "backend": "tpu", "config": "resnet50_s2d",
    "configs": {
        "resnet50": {"images_per_sec_per_chip": 2403.0, "mfu_pct": 15.0},
        "resnet50_s2d": {"images_per_sec_per_chip": 2433.7,
                         "mfu_pct": 15.2},
    },
    "mfu_pct": 15.2, "measured_at": "2026-07-29T20:41Z",
}


def step(name, js, at="2026-07-31T02:00:00Z"):
    return {"step": name, "at": at, "json": js}


def test_resnet_config_merge_updates_headline():
    out = merge(BASE, [step("resnet_bnsub", {
        "backend": "tpu",
        "configs": {"resnet50_s2d_bnsub": {
            "images_per_sec_per_chip": 2600.0, "mfu_pct": 16.2}},
    })])
    assert out["config"] == "resnet50_s2d_bnsub"
    assert out["value"] == 2600.0
    assert out["mfu_pct"] == 16.2
    assert out["vs_baseline"] == round(2600.0 / 2500.0, 3)
    # untouched families survive
    assert out["configs"]["resnet50"]["images_per_sec_per_chip"] == 2403.0
    assert out["configs"]["resnet50_s2d_bnsub"]["at"].startswith("2026-07-31")
    assert out["measured_at"] == "2026-07-31T02:00:00Z"


def test_family_step_lands_under_mapped_key():
    bert = {"metric": "bert_base_mlm_samples_per_sec_per_chip",
            "value": 416.4, "backend": "tpu", "mfu_pct": 18.16,
            "device_kind": "TPU v5 lite"}
    out = merge(BASE, [step("bert", bert)])
    assert out["configs"]["bert_base"]["value"] == 416.4
    assert "device_kind" not in out["configs"]["bert_base"]
    # resnet headline unchanged (no better resnet entry arrived)
    assert out["config"] == "resnet50_s2d"
    assert out["value"] == 2433.7


def test_experiment_steps_keep_descriptive_keys():
    out = merge(BASE, [
        step("lm_noffn_b12", {"value": 31000.0, "backend": "tpu"}),
        step("lm_pallas_off", {"value": 30000.0, "backend": "tpu"}),
    ])
    assert out["configs"]["llama_125m_noffn_b12"]["value"] == 31000.0
    assert out["configs"]["llama_125m_nopallas"]["value"] == 30000.0


def test_non_tpu_step_is_ignored():
    out = merge(BASE, [step("bert", {"value": 1.0, "backend": "cpu"})])
    assert "bert_base" not in out["configs"]
    assert out["measured_at"] == BASE["measured_at"]


def test_full_bench_headline_preferred():
    out = merge(BASE, [step("full_bench", {
        "backend": "tpu", "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 2450.0, "unit": "images/sec/chip", "vs_baseline": 0.98,
        "config": "resnet50_s2d", "mfu_pct": 15.3,
        "configs": {"resnet50_s2d": {"images_per_sec_per_chip": 2450.0,
                                     "mfu_pct": 15.3}},
    })])
    assert out["value"] == 2450.0
    assert out["configs"]["resnet50_s2d"]["images_per_sec_per_chip"] == 2450.0


def test_stale_unstamped_entry_never_takes_headline():
    # A pre-existing (unstamped) config faster than everything measured
    # this round must not silently become the freshly-stamped headline.
    base = dict(BASE, configs=dict(BASE["configs"],
                retired_variant={"images_per_sec_per_chip": 9999.0}))
    out = merge(base, [step("resnet_bnsub", {
        "backend": "tpu",
        "configs": {"resnet50_s2d_bnsub": {
            "images_per_sec_per_chip": 2300.0, "mfu_pct": 14.4}},
    })])
    assert out["config"] == "resnet50_s2d_bnsub"  # freshest measurement
    assert out["value"] == 2300.0
    assert out["configs"]["retired_variant"]["images_per_sec_per_chip"] \
        == 9999.0  # preserved, just not the headline


def test_implausible_resnet_entries_never_take_headline():
    out = merge(BASE, [step("resnet_s2d", {
        "backend": "tpu",
        "configs": {"resnet50_s2d": {
            "images_per_sec_per_chip": 73000.0, "implausible": True}},
    })])
    assert out["value"] == 2433.7  # flaky-tunnel artifact rejected


def test_cli_round_trip(tmp_path):
    rec = tmp_path / "last.json"
    rec.write_text(json.dumps(BASE))
    results = tmp_path / "results.jsonl"
    results.write_text(json.dumps(step("bert", {
        "value": 416.4, "backend": "tpu"})) + "\n")
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "merge_tpu_results.py")
    out = subprocess.run([sys.executable, tool, "--results", str(results),
                          "--record", str(rec)],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    persisted = json.loads(rec.read_text())
    assert persisted["configs"]["bert_base"]["value"] == 416.4
    assert persisted["merged_from"] == "chip_hunter"


def test_empty_results_is_an_error(tmp_path):
    rec = tmp_path / "last.json"
    rec.write_text(json.dumps(BASE))
    results = tmp_path / "results.jsonl"
    results.write_text("")
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "merge_tpu_results.py")
    out = subprocess.run([sys.executable, tool, "--results", str(results),
                          "--record", str(rec)],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 1
    assert json.loads(rec.read_text()) == BASE  # record untouched
