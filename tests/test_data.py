"""Input-pipeline tests: autoshard semantics, rebatch, prefetch, determinism."""

import numpy as np
import pytest

from tensorflow_train_distributed_tpu.data import (
    DataConfig,
    HostDataLoader,
    get_dataset,
    prefetch_to_device,
)
from tensorflow_train_distributed_tpu.data.datasets import (
    SyntheticBlobs,
    SyntheticMLM,
    SyntheticMNIST,
    SyntheticWMT,
)


class TestSources:
    def test_registry(self):
        for name in ("mnist", "blobs", "imagenet", "lm", "mlm", "wmt"):
            ds = get_dataset(name, num_examples=4)
            assert len(ds) == 4
            rec = ds[0]
            assert isinstance(rec, dict) and rec
        with pytest.raises(ValueError, match="Unknown dataset"):
            get_dataset("cifar")

    def test_deterministic_records(self):
        ds = SyntheticMNIST(num_examples=10)
        a, b = ds[3], ds[3]
        np.testing.assert_array_equal(a["image"], b["image"])
        assert a["label"] == 3

    def test_mlm_mask_recoverable(self):
        ds = SyntheticMLM(num_examples=2, seq_len=16)
        r = ds[0]
        masked = r["mask_weights"] > 0
        assert masked.sum() >= 1
        assert (r["input_ids"][masked] == SyntheticMLM.MASK_ID).all()
        # Palindrome: label at i equals label at L-1-i.
        np.testing.assert_array_equal(r["labels"], r["labels"][::-1])

    def test_wmt_mapping(self):
        ds = SyntheticWMT(num_examples=1, seq_len=8)
        r = ds[0]
        assert r["targets_in"][0] == SyntheticWMT.BOS
        assert r["targets_out"][-1] == SyntheticWMT.EOS
        assert len(r["inputs"]) == 8

    def test_slice_source_views(self):
        from tensorflow_train_distributed_tpu.data.datasets import (
            SliceSource, train_val_split,
        )

        ds = SyntheticBlobs(num_examples=100)
        train, val = train_val_split(ds, 0.1)
        assert len(train) == 90 and len(val) == 10
        # Views alias the base records with no overlap.
        np.testing.assert_array_equal(train[0]["x"], ds[0]["x"])
        np.testing.assert_array_equal(val[0]["x"], ds[90]["x"])
        with pytest.raises(IndexError):
            val[10]
        with pytest.raises(ValueError, match="training records"):
            train_val_split(ds, 0.5, min_val=100)
        with pytest.raises(ValueError, match="training records"):
            train_val_split(ds, 0.2, min_train=90)
        with pytest.raises(ValueError, match="invalid slice"):
            SliceSource(ds, 50, 20)


class TestHostDataLoader:
    def _loader(self, **kw):
        cfg = dict(global_batch_size=8, shuffle=True, seed=5, num_epochs=1)
        cfg.update(kw)
        return HostDataLoader(SyntheticBlobs(num_examples=64),
                              DataConfig(**cfg))

    def test_batch_shapes(self):
        batches = list(self._loader())
        assert len(batches) == 8  # 64 / 8
        assert batches[0]["x"].shape == (8, 16)
        assert batches[0]["label"].shape == (8,)

    def test_autoshard_disjoint_cover(self):
        """Two simulated processes cover the epoch disjointly (DATA policy)."""
        src = SyntheticBlobs(num_examples=32)
        cfg = DataConfig(global_batch_size=8, shuffle=True, seed=9, num_epochs=1)
        seen = []
        for p in range(2):
            loader = HostDataLoader(src, cfg, process_index=p, process_count=2)
            for batch in loader:
                assert batch["x"].shape[0] == 4  # rebatch: 8 global / 2 hosts
                seen.extend(batch["x"][:, 0].tolist())
        # All 32 distinct first-coords seen exactly once.
        assert len(seen) == 32 and len(set(seen)) == 32

    def test_shuffle_differs_by_epoch_and_seed(self):
        l1 = list(self._loader(num_epochs=2))
        first, second = l1[:8], l1[8:]
        assert not np.array_equal(first[0]["x"], second[0]["x"])
        l2 = list(self._loader(seed=6))
        assert not np.array_equal(l1[0]["x"], l2[0]["x"])
        # Same seed → identical stream.
        l3 = list(self._loader())
        np.testing.assert_array_equal(l1[0]["x"], l3[0]["x"])

    def test_bad_divisibility(self):
        with pytest.raises(ValueError, match="not divisible"):
            HostDataLoader(SyntheticBlobs(num_examples=8),
                           DataConfig(global_batch_size=3),
                           process_index=0, process_count=2)

    def test_dynamic_shapes_never_emitted(self):
        # drop_remainder=False must keep shapes static: the final batch is
        # padded, never shrunk (SPMD recompiles per shape otherwise).
        loader = HostDataLoader(
            SyntheticBlobs(num_examples=10),
            DataConfig(global_batch_size=4, shuffle=False, num_epochs=1,
                       drop_remainder=False))
        shapes = {b["x"].shape[0] for b in loader}
        assert shapes == {4}


class TestPadRemainder:
    """drop_remainder=False: pad-and-mask final batch (SURVEY §7 HP2)."""

    def _loader(self, n=10, gbs=4, **kw):
        return HostDataLoader(
            SyntheticBlobs(num_examples=n),
            DataConfig(global_batch_size=gbs, shuffle=False, num_epochs=1,
                       drop_remainder=False), **kw)

    def test_covers_every_example_exactly_once(self):
        loader = self._loader()
        batches = list(loader)
        assert len(batches) == 3 == loader.steps_per_epoch()
        w = np.concatenate([b["sample_weight"] for b in batches])
        labels = np.concatenate([b["label"] for b in batches])
        assert w.sum() == 10  # every real example weighted once
        np.testing.assert_array_equal(w, [1] * 10 + [0, 0])
        # Pad rows repeat the last real record (valid data, weight 0).
        src = SyntheticBlobs(num_examples=10)
        np.testing.assert_array_equal(
            labels[:10], [src[i]["label"] for i in range(10)])
        assert labels[10] == labels[9] == labels[11]

    def test_exact_multiple_yields_all_ones(self):
        loader = self._loader(n=8, gbs=4)
        batches = list(loader)
        assert len(batches) == 2
        for b in batches:
            assert (b["sample_weight"] == 1.0).all()

    def test_multiprocess_consistent_batch_counts(self):
        # n=9 over 2 processes: shards of 5 and 4; both must run the SAME
        # number of batches (SPMD deadlock otherwise), short shards pad.
        loaders = [
            HostDataLoader(
                SyntheticBlobs(num_examples=9),
                DataConfig(global_batch_size=4, shuffle=False,
                           num_epochs=1, drop_remainder=False),
                process_index=p, process_count=2)
            for p in range(2)
        ]
        per_proc = [list(ld) for ld in loaders]
        assert len(per_proc[0]) == len(per_proc[1]) == \
            loaders[0].steps_per_epoch() == loaders[1].steps_per_epoch()
        total_w = sum(float(b["sample_weight"].sum())
                      for bs in per_proc for b in bs)
        assert total_w == 9  # global coverage exact

    def test_iter_from_matches_fresh_stream(self):
        loader = self._loader()
        fresh = list(loader)[1:]
        resumed = list(loader.iter_from(1))
        assert len(fresh) == len(resumed)
        for a, b in zip(fresh, resumed):
            np.testing.assert_array_equal(a["sample_weight"],
                                          b["sample_weight"])
            np.testing.assert_array_equal(a["x"], b["x"])

    def test_weight_key_collision_rejected(self):
        class _Src:
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return {"x": np.zeros(2, np.float32),
                        "sample_weight": np.float32(1)}

        loader = HostDataLoader(
            _Src(), DataConfig(global_batch_size=4, shuffle=False,
                               num_epochs=1, drop_remainder=False))
        with pytest.raises(ValueError, match="sample_weight"):
            next(iter(loader))


class TestPrefetch:
    def test_prefetch_yields_sharded(self, mesh8):
        loader = HostDataLoader(
            SyntheticBlobs(num_examples=32),
            DataConfig(global_batch_size=8, num_epochs=1),
        )
        n = 0
        for dev_batch in prefetch_to_device(iter(loader), mesh8, size=2):
            assert len(dev_batch["x"].addressable_shards) == 8
            assert dev_batch["x"].shape == (8, 16)
            n += 1
        assert n == 4

    def test_prefetch_propagates_errors(self, mesh8):
        def bad_iter():
            yield {"x": np.ones((8, 4), np.float32)}
            raise RuntimeError("source died")

        it = prefetch_to_device(bad_iter(), mesh8, size=1)
        next(it)
        with pytest.raises(RuntimeError, match="source died"):
            for _ in it:
                pass


class TestMidEpochResume:
    """iter_from: BackupAndRestore-style mid-run data positioning."""

    def _loader(self, **kw):
        cfg = DataConfig(global_batch_size=8, seed=3, **kw)
        return HostDataLoader(SyntheticBlobs(num_examples=64), cfg)

    def test_iter_from_zero_matches_fresh_stream(self):
        a = [b["x"] for _, b in zip(range(10), iter(self._loader()))]
        b = [b["x"] for _, b in zip(range(10), self._loader().iter_from(0))]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    @pytest.mark.parametrize("k", [3, 8, 13])  # mid-epoch, boundary, epoch 2
    def test_iter_from_k_skips_exactly_k(self, k):
        full = [b["x"] for _, b in zip(range(20), iter(self._loader()))]
        resumed = [b["x"] for _, b in zip(range(20 - k),
                                          self._loader().iter_from(k))]
        assert len(resumed) == 20 - k
        for x, y in zip(full[k:], resumed):
            np.testing.assert_array_equal(x, y)

    def test_iter_from_past_end_is_empty(self):
        loader = self._loader(num_epochs=2)  # 8 steps/epoch → 16 steps total
        assert list(loader.iter_from(16)) == []
        assert len(list(loader.iter_from(15))) == 1


class TestMixtureSource:
    """Weighted multi-corpus mixtures (LLM-pretrain data recipe)."""

    @staticmethod
    def _tagged(tag, n):
        class _Src:
            def __len__(self):
                return n

            def __getitem__(self, i):
                if not 0 <= i < n:
                    raise IndexError(i)
                return {"tag": np.asarray([tag], np.int32),
                        "pos": np.asarray([i], np.int32)}
        return _Src()

    def test_ratios_and_determinism(self):
        from tensorflow_train_distributed_tpu.data import MixtureSource

        mix = MixtureSource([self._tagged(0, 100), self._tagged(1, 100)],
                            weights=[3, 1], seed=7, num_examples=4000)
        tags = np.array([int(mix[i]["tag"][0]) for i in range(len(mix))])
        frac = (tags == 0).mean()
        assert 0.70 < frac < 0.80, frac  # ~0.75 by weight
        mix2 = MixtureSource([self._tagged(0, 100), self._tagged(1, 100)],
                             weights=[3, 1], seed=7, num_examples=4000)
        tags2 = np.array([int(mix2[i]["tag"][0]) for i in range(200)])
        np.testing.assert_array_equal(tags[:200], tags2)  # seeded schedule

    def test_sequential_positions_wrap_small_corpus(self):
        from tensorflow_train_distributed_tpu.data import MixtureSource

        small = self._tagged(1, 4)  # exhausted and wrapped many times
        mix = MixtureSource([self._tagged(0, 64), small], weights=[1, 1],
                            seed=0, num_examples=64)
        seen = [int(mix[i]["pos"][0]) for i in range(64)
                if int(mix[i]["tag"][0]) == 1]
        # Within-component positions are sequential modulo the corpus size.
        assert seen == [i % 4 for i in range(len(seen))]

    def test_composes_with_loader_and_resume(self):
        from tensorflow_train_distributed_tpu.data import (
            DataConfig, HostDataLoader, MixtureSource,
        )

        mix = MixtureSource([self._tagged(0, 40), self._tagged(1, 40)],
                            seed=3, num_examples=80)
        cfg = DataConfig(global_batch_size=8, seed=5)
        full = [b["tag"].sum() for _, b in zip(range(6),
                                               HostDataLoader(mix, cfg))]
        again = [b["tag"].sum() for _, b in zip(range(6),
                                                HostDataLoader(mix, cfg))]
        assert full == again  # deterministic through the shuffling loader
        # Mid-epoch resume: iter_from(k) reproduces batches k..n exactly.
        resumed = [b["tag"].sum() for _, b in zip(
            range(3), HostDataLoader(mix, cfg).iter_from(3))]
        assert resumed == full[3:6]

    def test_prefix_stable_when_budget_extended(self):
        from tensorflow_train_distributed_tpu.data import MixtureSource

        srcs = lambda: [self._tagged(0, 50), self._tagged(1, 50)]  # noqa
        short = MixtureSource(srcs(), weights=[2, 1], seed=11,
                              num_examples=60)
        longer = MixtureSource(srcs(), weights=[2, 1], seed=11,
                               num_examples=120)
        a = [(int(short[i]["tag"][0]), int(short[i]["pos"][0]))
             for i in range(60)]
        b = [(int(longer[i]["tag"][0]), int(longer[i]["pos"][0]))
             for i in range(60)]
        assert a == b  # extending the budget must not rescramble history

    def test_validation(self):
        from tensorflow_train_distributed_tpu.data import MixtureSource

        with pytest.raises(ValueError, match="at least one"):
            MixtureSource([])
        with pytest.raises(ValueError, match="weights"):
            MixtureSource([self._tagged(0, 4)], weights=[1, 2])
        with pytest.raises(ValueError, match="> 0"):
            MixtureSource([self._tagged(0, 4)], weights=[0.0])
        with pytest.raises(IndexError):
            MixtureSource([self._tagged(0, 4)], num_examples=8)[8]
        with pytest.raises(ValueError, match="empty"):
            MixtureSource([self._tagged(0, 4), self._tagged(1, 0)])
