"""Paged KV cache: block pool, radix sharing, and engine parity.

North star (the ISSUE 6 acceptance bar): with paging ON (the default),
engine outputs are BITWISE-IDENTICAL to the linear-cache engine for
greedy, seeded sampling, and speculative serving — including mid-stream
cancel and staged-prefill interleave — and ``TTD_NO_PAGED_KV=1`` /
``paged=False`` restores the linear engine byte-for-byte.  The host
allocator (``serving_kv``) is pinned separately: radix
insert/match/evict invariants, copy-on-write divergence after a shared
prefix, and eviction-under-pressure REFUSING admission rather than
corrupting a live lane.

Fast tier: the host-only allocator/radix tests (no device work) plus
one tiny paged-vs-linear engine parity run.  The full matrix (sampling,
speculative, cancel, interleave, pressure) is slow-tier.
"""

import dataclasses

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from tensorflow_train_distributed_tpu import serving_kv
from tensorflow_train_distributed_tpu.models.llama import (
    LLAMA_PRESETS,
    LlamaModel,
)
from tensorflow_train_distributed_tpu.serving import ServingEngine

CFG = LLAMA_PRESETS["llama_tiny"]


@pytest.fixture(scope="module")
def params():
    return LlamaModel(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


# ── fast tier: host-only pool + radix invariants ───────────────────────


def test_pool_alloc_ref_free_cycle():
    pool = serving_kv.KVBlockPool(4, 8)
    assert pool.free_blocks() == 4 and pool.blocks_in_use() == 0
    a = pool.alloc(3)
    assert sorted(a) == [1, 2, 3]          # block 0 is scratch: never
    assert pool.alloc(2) is None           # all-or-nothing
    pool.ref(a[0])
    pool.deref(a[0])
    assert pool.blocks_in_use() == 3       # still lane-held
    for b in a:
        pool.deref(b)
    assert pool.free_blocks() == 4
    with pytest.raises(ValueError, match="free block"):
        pool.deref(a[0])


def test_radix_insert_match_evict_invariants():
    pool = serving_kv.KVBlockPool(8, 2)
    idx = serving_kv.RadixPrefixIndex(pool)
    toks = [1, 2, 3, 4, 5, 6]
    blocks = pool.alloc(3)
    idx.insert(toks, lambda j: blocks[j])
    idx.check_invariants()
    # Match is block-aligned and must leave >= 1 suffix token.
    assert idx.match(toks + [9]) == (6, blocks)
    assert idx.match(toks) == (4, blocks[:2])       # strict-extension cap
    assert idx.match([1, 2, 9, 9, 9])[0] == 2
    assert idx.match([9, 9, 9])[0] == 0
    # Lane releases its refs: blocks become tree-held (cached).
    for b in blocks:
        pool.deref(b)
    assert pool.blocks_in_use() == 3
    # A matching lane re-refs the shared blocks; they are then pinned
    # against eviction.
    m, shared = idx.match(toks + [7])
    for b in shared:
        pool.ref(b)
    assert idx.evict_for(8) < 8            # cannot evict pinned chain
    idx.check_invariants()
    for b in shared:
        pool.deref(b)
    # Fully retired: eviction drains the whole subtree, leaves first.
    evicted = idx.evict_for(8)
    assert evicted == 3 and pool.free_blocks() == 8 and len(idx) == 0
    idx.check_invariants()


def test_radix_lru_evicts_least_recent_leaf():
    pool = serving_kv.KVBlockPool(4, 2)
    idx = serving_kv.RadixPrefixIndex(pool)
    a = pool.alloc(1)
    idx.insert([1, 1], lambda j: a[j])
    b = pool.alloc(1)
    idx.insert([2, 2], lambda j: b[j])
    for blk in a + b:
        pool.deref(blk)
    idx.match([1, 1, 9])                   # refresh [1, 1]'s recency
    assert idx.evict_for(pool.free_blocks() + 1) == 1
    assert idx.match([2, 2, 9])[0] == 0    # LRU victim was [2, 2]
    assert idx.match([1, 1, 9])[0] == 2
    idx.check_invariants()


def test_radix_dedup_keeps_canonical_block():
    pool = serving_kv.KVBlockPool(4, 2)
    idx = serving_kv.RadixPrefixIndex(pool)
    a = pool.alloc(1)
    assert idx.insert([5, 6], lambda j: a[j]) == 1
    dup = pool.alloc(1)
    # Same chunk from a second lane: existing node stays canonical,
    # nothing new is cached, the duplicate stays lane-owned only.
    assert idx.insert([5, 6], lambda j: dup[j]) == 0
    assert idx.match([5, 6, 7]) == (2, a)
    pool.deref(dup[0])
    assert pool.free_blocks() == 3         # dup freed, a still 2-held
    idx.check_invariants()


def test_lane_kv_table_padding():
    kv = serving_kv.LaneKV(request_id=1, matched=4, shared=[3, 7],
                           owned=[5])
    assert kv.table(5) == [3, 7, 5, 0, 0]
    assert kv.blocks() == [3, 7, 5]


def _ref(params, prompt, max_new, **kw):
    from tensorflow_train_distributed_tpu.models.generate import generate

    return np.asarray(generate(
        CFG, params, jnp.asarray([prompt], jnp.int32), max_new,
        **kw))[0].tolist()


def _serve(params, reqs, *, seeds=None, **kw):
    eng = ServingEngine(CFG, params, **kw)
    seeds = seeds or [None] * len(reqs)
    ids = [eng.submit(p, m, seed=s) for (p, m), s in zip(reqs, seeds)]
    out = eng.run()
    return [out[i] for i in ids], eng


def test_paged_engine_smoke_matches_generate(params):
    """Fast-tier canary: tiny paged engine run, token-identical to
    generate() and to the linear engine, with the pool drained back to
    the radix cache afterwards."""
    rng = np.random.default_rng(0)
    reqs = [(list(rng.integers(1, 200, 5)), 4),
            (list(rng.integers(1, 200, 3)), 5)]
    out, eng = _serve(params, reqs, slots=2, cache_len=32, chunk=2,
                      prompt_buckets=(8,), kv_block_size=4)
    assert eng.paged
    lin, _ = _serve(params, reqs, slots=2, cache_len=32, chunk=2,
                    prompt_buckets=(8,), kv_block_size=4, paged=False)
    for o, l, (p, m) in zip(out, lin, reqs):
        assert o == l == _ref(params, p, m)
    # Lanes released; what's in use is exactly the radix-cached blocks.
    assert eng.kv_blocks_in_use() == eng._radix.cached_blocks()
    eng._radix.check_invariants()


def test_fused_kill_switch_bitwise_and_attrs(params, monkeypatch):
    """Fast-tier canary for the fused paged-attention plumbing: on CPU
    the fused kernel never engages (``fused_attn()`` False), so the
    default engine and the ``TTD_NO_FUSED_ATTN=1`` engine must be
    BITWISE identical — the kill-switch plumbing changes dispatch,
    never math; and ``kv_pool_bytes`` truthfully reports the pool's
    device footprint (0 on the linear engine)."""
    rng = np.random.default_rng(7)
    reqs = [(list(rng.integers(1, 200, 5)), 4),
            (list(rng.integers(1, 200, 3)), 5)]
    out, eng = _serve(params, reqs, slots=2, cache_len=32, chunk=2,
                      prompt_buckets=(8,), kv_block_size=4)
    assert eng.fused_attn() is False          # CPU: gather path
    assert eng.kv_pool_bytes() > 0
    monkeypatch.setenv("TTD_NO_FUSED_ATTN", "1")
    killed, eng_k = _serve(params, reqs, slots=2, cache_len=32, chunk=2,
                           prompt_buckets=(8,), kv_block_size=4)
    assert eng_k.fused_attn() is False
    assert killed == out
    monkeypatch.delenv("TTD_NO_FUSED_ATTN")
    lin, eng_l = _serve(params, reqs, slots=2, cache_len=32, chunk=2,
                        prompt_buckets=(8,), kv_block_size=4,
                        paged=False)
    assert eng_l.kv_pool_bytes() == 0 and eng_l.fused_attn() is False


ICFG = dataclasses.replace(CFG, kv_cache_int8=True)


def _serve_cfg(cfg, params, reqs, *, seeds=None, **kw):
    eng = ServingEngine(cfg, params, **kw)
    seeds = seeds or [None] * len(reqs)
    ids = [eng.submit(p, m, seed=s) for (p, m), s in zip(reqs, seeds)]
    out = eng.run()
    return [out[i] for i in ids], eng


def _ref_cfg(cfg, params, prompt, max_new, **kw):
    from tensorflow_train_distributed_tpu.models.generate import generate

    return np.asarray(generate(
        cfg, params, jnp.asarray([prompt], jnp.int32), max_new,
        **kw))[0].tolist()


def test_int8_paged_engine_smoke_matches_generate(params):
    """Fast-tier canary for the int8 paged pool: a kv_cache_int8
    config SERVES through the engine (the old rejection is lifted),
    the pool stores int8 rows + a parallel f32 scale pool, and greedy
    outputs are token-identical to generate() with the same config
    (the linear-cache int8 recipe applied block-wise — same quantized
    bytes, different layout)."""
    rng = np.random.default_rng(1)
    reqs = [(list(rng.integers(1, 200, 5)), 4),
            (list(rng.integers(1, 200, 3)), 5)]
    out, eng = _serve_cfg(ICFG, params, reqs, slots=2, cache_len=32,
                          chunk=2, prompt_buckets=(8,), kv_block_size=4)
    assert eng.paged and eng.kv_cache_int8
    for o, (p, m) in zip(out, reqs):
        assert o == _ref_cfg(ICFG, params, p, m)
    kinds = {p[-1].key: leaf.dtype for p, leaf in
             jax.tree_util.tree_flatten_with_path(eng._cache)[0]}
    assert kinds["key_pool"] == jnp.int8
    assert kinds["value_pool"] == jnp.int8
    assert kinds["kv_pool_scales"] == jnp.float32
    # int8 pool + f32 scales < the fp32 pool it replaces.
    _, eng_fp = _serve_cfg(CFG, params, reqs, slots=2, cache_len=32,
                           chunk=2, prompt_buckets=(8,),
                           kv_block_size=4)
    assert eng.kv_pool_bytes() < eng_fp.kv_pool_bytes()


# ── slow tier: the full parity matrix ──────────────────────────────────

pytestmark_slow = pytest.mark.slow


@pytest.mark.slow
@pytest.mark.parametrize("sampling", [
    dict(),
    dict(temperature=0.9, top_k=16),
    dict(temperature=0.7, top_p=0.9),
])
def test_paged_matches_linear_with_refills(params, sampling):
    """Six mixed requests through two slots (every lane refills):
    bitwise identity paged vs linear for greedy and seeded sampling."""
    rng = np.random.default_rng(1)
    reqs = [(list(rng.integers(1, 200, n)), m)
            for n, m in [(5, 6), (3, 9), (7, 4), (4, 12), (6, 1),
                         (2, 0)]]
    seeds = [11, 22, 33, 44, 55, 66]
    kw = dict(slots=2, cache_len=64, chunk=4, prompt_buckets=(8, 16),
              kv_block_size=4, **sampling)
    out, _ = _serve(params, reqs, seeds=seeds, **kw)
    lin, _ = _serve(params, reqs, seeds=seeds, paged=False, **kw)
    assert out == lin


@pytest.mark.slow
def test_paged_matches_linear_speculative(params):
    """Speculative serving (self-draft, full acceptance) and a
    DISAGREEING draft: paged == linear bitwise, greedy and sampled."""
    dcfg = LLAMA_PRESETS["llama_tiny_scan"]
    dparams = LlamaModel(dcfg).init(
        jax.random.PRNGKey(9), jnp.zeros((1, 4), jnp.int32))["params"]
    rng = np.random.default_rng(2)
    reqs = [(list(rng.integers(1, 200, n)), m)
            for n, m in [(5, 8), (7, 6), (3, 9)]]
    for draft_cfg, draft_params in ((CFG, params), (dcfg, dparams)):
        for sampling in (dict(), dict(temperature=0.8, top_k=20)):
            kw = dict(slots=2, cache_len=48, chunk=3,
                      prompt_buckets=(8,), kv_block_size=4,
                      draft_config=draft_cfg, draft_params=draft_params,
                      speculative_k=3, **sampling)
            out, eng = _serve(params, reqs, seeds=[1, 2, 3], **kw)
            lin, _ = _serve(params, reqs, seeds=[1, 2, 3], paged=False,
                            **kw)
            assert out == lin
            assert eng.spec_stats["rounds"] >= 1


@pytest.mark.slow
def test_paged_matches_linear_mid_stream_cancel(params):
    """Cancel mid-decode and mid-staged-prefill: the surviving
    requests' outputs stay bitwise-identical paged vs linear, and the
    cancelled lanes' blocks return to the pool."""
    rng = np.random.default_rng(3)
    long_prompt = list(rng.integers(1, 200, 24))
    short = [list(rng.integers(1, 200, 5)) for _ in range(3)]

    def run(paged):
        eng = ServingEngine(CFG, params, slots=2, cache_len=64, chunk=3,
                            prompt_buckets=(8,), prefill_chunk=8,
                            kv_block_size=4, paged=paged)
        a = eng.submit(short[0], 10)
        b = eng.submit(short[1], 10)
        eng.serve_step()
        c = eng.submit(long_prompt, 8)     # stages behind the decode
        d = eng.submit(short[2], 6)
        eng.serve_step()
        assert eng.cancel(c)               # mid-staged-prefill
        eng.serve_step()
        assert eng.cancel(a)               # mid-decode
        out = {}
        while eng.pending():
            out.update(eng.serve_step())
        return out.get(b), out.get(d), eng

    b_p, d_p, eng_p = run(True)
    b_l, d_l, _ = run(False)
    assert b_p == b_l and d_p == d_l
    assert all(kv is None for kv in eng_p._lane_kv)
    eng_p._radix.check_invariants()


@pytest.mark.slow
@pytest.mark.parametrize("sampling", [dict(),
                                      dict(temperature=0.8, top_k=12)])
def test_paged_matches_linear_staged_interleave(params, sampling):
    """A long prompt admitted mid-stream under the interleaved prefill
    scheduler (several budget installments): bitwise identity paged vs
    linear for the long request AND the active lanes around it."""
    rng = np.random.default_rng(4)
    active = [(list(rng.integers(1, 200, 6)), 14) for _ in range(2)]
    long_req = (list(rng.integers(1, 200, 30)), 6)

    def run(paged):
        eng = ServingEngine(CFG, params, slots=3, cache_len=64, chunk=3,
                            prompt_buckets=(8,), prefill_chunk=8,
                            kv_block_size=4, paged=paged, **sampling)
        ids = [eng.submit(p, m, seed=7 + i)
               for i, (p, m) in enumerate(active)]
        eng.serve_step()
        ids.append(eng.submit(*long_req, seed=99))
        out = {}
        while eng.pending():
            out.update(eng.serve_step())
        return [out[i] for i in ids]

    assert run(True) == run(False)


@pytest.mark.slow
def test_copy_on_write_divergence_after_shared_prefix(params):
    """Two requests share a block-aligned prefix then diverge: each
    decodes its own continuation (bitwise = generate()), and the
    SHARED physical blocks' bytes are untouched by either lane — the
    allocation-time copy-on-write contract."""
    rng = np.random.default_rng(5)
    pre = list(rng.integers(1, 200, 8))     # 2 full blocks at bs=4
    a = pre + list(rng.integers(1, 200, 3))
    b = pre + list(rng.integers(1, 200, 3))
    eng = ServingEngine(CFG, params, slots=2, cache_len=48, chunk=3,
                        prompt_buckets=(16,), kv_block_size=4)
    ia = eng.submit(a, 6)
    out1 = eng.run()
    # The first request seeded the radix; snapshot the shared blocks'
    # bytes before the second (sharing) request runs.
    matched, shared = eng._radix.match(b)
    assert matched == 8 and len(shared) == 2

    def pool_rows(blocks):
        idx = jnp.asarray(blocks)
        rows = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                eng._cache)[0]:
            name = getattr(path[-1], "key", "")
            if name in ("key_pool", "value_pool"):
                rows[ServingEngine._path_key(path)] = np.asarray(
                    jnp.take(leaf, idx, axis=leaf.ndim - 4))
        return rows

    before = pool_rows(shared)
    ib = eng.submit(b, 6)
    out2 = eng.run()
    after = pool_rows(shared)
    assert out1[ia] == _ref(params, a, 6)
    assert out2[ib] == _ref(params, b, 6)
    assert eng.kv_stats["prefix_hit_tokens"] >= 8
    for k in before:
        assert np.array_equal(before[k], after[k]), f"shared {k} written"


@pytest.mark.slow
def test_eviction_under_pressure_refuses_admission(params):
    """A pool too small for two lanes: the second request is REFUSED
    admission (queued, counted) until the first retires — outputs stay
    exactly the linear engine's, and no live lane is ever corrupted.
    Retired prefixes evict LRU to make room."""
    rng = np.random.default_rng(6)
    reqs = [(list(rng.integers(1, 200, 6)), 8) for _ in range(3)]
    eng = ServingEngine(CFG, params, slots=2, cache_len=32, chunk=3,
                        prompt_buckets=(8,), kv_block_size=4,
                        kv_pool_blocks=4)    # one lane's worth
    ids = [eng.submit(p, m) for p, m in reqs]
    out = eng.run()
    lin, _ = _serve(params, reqs, slots=2, cache_len=32, chunk=3,
                    prompt_buckets=(8,), paged=False)
    assert [out[i] for i in ids] == lin
    assert eng.kv_stats["alloc_refusals"] >= 1
    assert eng.kv_stats["evictions"] >= 1
    assert eng.kv_blocks_in_use() <= eng.kv_blocks_total()
    eng._radix.check_invariants()
    # A request that could NEVER fit is rejected at submit, not queued
    # forever.
    with pytest.raises(ValueError, match="KV blocks"):
        eng.submit(list(rng.integers(1, 200, 8)), 12)


@pytest.mark.slow
def test_kill_switch_restores_linear_engine(params, monkeypatch):
    """TTD_NO_PAGED_KV=1 at construction: the engine IS the linear
    engine (no pool, no radix, byte-for-byte the old behavior)."""
    monkeypatch.setenv("TTD_NO_PAGED_KV", "1")
    eng = ServingEngine(CFG, params, slots=2, cache_len=32, chunk=2,
                        prompt_buckets=(8,))
    assert not eng.paged
    assert eng.kv_blocks_total() == 0 and eng.kv_blocks_in_use() == 0
    rid = eng.submit([1, 2, 3], 4)
    assert eng.run()[rid] == _ref(params, [1, 2, 3], 4)


@pytest.mark.slow
def test_linear_prefix_cache_is_lru_bounded(params):
    """The linear path's ``_prefix_caches`` no longer leaks: preloads
    past ``prefix_cache_limit`` evict the least recently matched."""
    eng = ServingEngine(CFG, params, slots=1, cache_len=32, chunk=2,
                        prompt_buckets=(8,), paged=False,
                        prefix_cache_limit=2)
    eng.preload_prefix([1, 1])
    eng.preload_prefix([2, 2])
    eng._match_prefix([1, 1, 9], touch=True)   # refresh [1, 1]
    eng.preload_prefix([3, 3])                 # evicts [2, 2]
    assert len(eng._prefix_caches) == 2
    assert eng._match_prefix([2, 2, 9])[0] == 0
    assert eng._match_prefix([1, 1, 9])[0] == 2
    assert eng._match_prefix([3, 3, 9])[0] == 2


@pytest.mark.slow
def test_paged_rejects_nothing_linear_accepts(params):
    """Engine-level guards carry over: the paged engine screens the
    same configs the linear one does, plus its own block knobs."""
    with pytest.raises(ValueError, match="kv_block_size"):
        ServingEngine(CFG, params, slots=1, cache_len=16,
                      prompt_buckets=(8,), kv_block_size=0)
    with pytest.raises(ValueError, match="kv_pool_blocks"):
        ServingEngine(CFG, params, slots=1, cache_len=16,
                      prompt_buckets=(8,), kv_pool_blocks=0)
    wcfg = dataclasses.replace(CFG, sliding_window=8)
    with pytest.raises(ValueError, match="sliding_window"):
        ServingEngine(wcfg, params)


@pytest.mark.slow
@pytest.mark.parametrize("sampling", [
    dict(),
    dict(temperature=0.9, top_k=16),
])
def test_int8_paged_matches_linear_with_refills(params, sampling):
    """kv_cache_int8 through two slots with every lane refilling:
    paged == the int8 LINEAR engine bitwise (same quantized rows, same
    scales, different physical layout) for greedy and seeded
    sampling — the 'int8-pool parity pinned against the linear-cache
    kv_cache_int8 path at matched config' acceptance bar."""
    rng = np.random.default_rng(11)
    reqs = [(list(rng.integers(1, 200, n)), m)
            for n, m in [(5, 6), (3, 9), (7, 4), (4, 8), (6, 1)]]
    seeds = [11, 22, 33, 44, 55]
    kw = dict(slots=2, cache_len=64, chunk=4, prompt_buckets=(8, 16),
              kv_block_size=4, **sampling)
    out, _ = _serve_cfg(ICFG, params, reqs, seeds=seeds, **kw)
    lin, _ = _serve_cfg(ICFG, params, reqs, seeds=seeds, paged=False,
                        **kw)
    assert out == lin
    # And token-identical to the shared-index generate() path (greedy
    # only: generate's sampling streams are per-batch, not comparable).
    if not sampling:
        for o, (p, m) in zip(out, reqs):
            assert o == _ref_cfg(ICFG, params, p, m)


@pytest.mark.slow
def test_int8_paged_speculative_and_prefix(params):
    """int8 composes with the rest of the paged feature set:
    speculative serving (int8 target AND int8 draft — shared block
    tables, both pools quantized) and radix prefix sharing (the
    ``_gather_prefix`` copy carries the scale rows, so a prefix hit
    reads the exact bytes the original prefill quantized)."""
    rng = np.random.default_rng(12)
    reqs = [(list(rng.integers(1, 200, n)), m)
            for n, m in [(5, 8), (7, 6), (3, 9)]]
    kw = dict(slots=2, cache_len=48, chunk=3, prompt_buckets=(8,),
              kv_block_size=4, draft_config=ICFG, draft_params=params,
              speculative_k=3)
    out, eng = _serve_cfg(ICFG, params, reqs, seeds=[1, 2, 3], **kw)
    lin, _ = _serve_cfg(ICFG, params, reqs, seeds=[1, 2, 3],
                        paged=False, **kw)
    assert out == lin
    assert eng.spec_stats["rounds"] >= 1
    # Prefix sharing: a block-aligned shared prefix hits warm int8 KV
    # and the continuation still equals generate().
    pre = list(rng.integers(1, 200, 8))
    a = pre + list(rng.integers(1, 200, 3))
    b = pre + list(rng.integers(1, 200, 3))
    eng2 = ServingEngine(ICFG, params, slots=2, cache_len=48, chunk=3,
                         prompt_buckets=(16,), kv_block_size=4)
    ia = eng2.submit(a, 6)
    o1 = eng2.run()
    ib = eng2.submit(b, 6)
    o2 = eng2.run()
    assert o1[ia] == _ref_cfg(ICFG, params, a, 6)
    assert o2[ib] == _ref_cfg(ICFG, params, b, 6)
    assert eng2.kv_stats["prefix_hit_tokens"] >= 8
    # preload_prefix seeds the same int8 pool.
    eng3 = ServingEngine(ICFG, params, slots=2, cache_len=48, chunk=3,
                         prompt_buckets=(16,), kv_block_size=4)
    eng3.preload_prefix(pre)
    ic = eng3.submit(a, 6)
    assert eng3.run()[ic] == _ref_cfg(ICFG, params, a, 6)


@pytest.mark.slow
def test_fused_interpret_parity_matrix(params, monkeypatch):
    """The fused-kernel serving parity bar, exercised FOR REAL on CPU:
    ``TTD_FUSED_ATTN_INTERPRET=1`` compiles the decode programs with
    the interpret-mode fused kernel, and every scenario — greedy,
    seeded sampling, speculative, staged-prefill interleave,
    prefix-hit admission, mid-stream cancel, int8 pool — must produce
    the SAME TOKENS as the ``TTD_NO_FUSED_ATTN=1`` XLA block-gather
    leg.  Both legs are deterministic functions of the same inputs, so
    token equality here is a stable pin, not a flaky race."""
    rng = np.random.default_rng(13)
    pre = list(rng.integers(1, 200, 8))
    reqs = [(list(rng.integers(1, 200, 5)), 8),
            (pre + list(rng.integers(1, 200, 3)), 6),
            (pre + list(rng.integers(1, 200, 4)), 5)]
    long_req = (list(rng.integers(1, 200, 24)), 6)

    def scenario(cfg, **kw):
        eng = ServingEngine(cfg, params, slots=2, cache_len=64, chunk=3,
                            prompt_buckets=(8,), prefill_chunk=8,
                            kv_block_size=4, **kw)
        ids = [eng.submit(p, m, seed=5 + i)
               for i, (p, m) in enumerate(reqs)]
        eng.serve_step()
        ids.append(eng.submit(*long_req, seed=99))  # staged interleave
        victim = eng.submit(list(rng.integers(1, 200, 5)), 9, seed=42)
        eng.serve_step()
        assert eng.cancel(victim)                   # mid-stream cancel
        out = {}
        while eng.pending():
            out.update(eng.serve_step())
        return [out[i] for i in ids], eng

    def legs(cfg, **kw):
        monkeypatch.setenv("TTD_FUSED_ATTN_INTERPRET", "1")
        fused, eng_f = scenario(cfg, **kw)
        assert eng_f.fused_attn() is True
        monkeypatch.delenv("TTD_FUSED_ATTN_INTERPRET")
        monkeypatch.setenv("TTD_NO_FUSED_ATTN", "1")
        gather, eng_g = scenario(cfg, **kw)
        assert eng_g.fused_attn() is False
        monkeypatch.delenv("TTD_NO_FUSED_ATTN")
        return fused, gather

    for cfg in (CFG, ICFG):
        fused, gather = legs(cfg)                       # greedy
        assert fused == gather
        fused, gather = legs(cfg, temperature=0.8, top_k=16)  # sampled
        assert fused == gather
    fused, gather = legs(CFG, draft_config=CFG, draft_params=params,
                         speculative_k=3)               # speculative
    assert fused == gather


@pytest.mark.slow
def test_engine_accepts_int8_rejects_windows(params):
    """The PR-11 screen shape: kv_cache_int8 configs construct and
    serve (the stale 'serves through models.generate' claim is gone);
    rolling-window/sink configs still fail loudly, without blaming
    int8."""
    eng = ServingEngine(ICFG, params, slots=1, cache_len=16, chunk=2,
                        prompt_buckets=(8,))
    assert eng.kv_cache_int8
    wcfg = dataclasses.replace(CFG, sliding_window=8)
    with pytest.raises(ValueError, match="sliding_window") as ei:
        ServingEngine(wcfg, params)
    assert "kv_cache_int8 is supported" in str(ei.value)


@pytest.mark.slow
def test_paged_metrics_accessors_track_pool(params):
    """kv_blocks_in_use/total + hit/eviction counters feed /metrics;
    check they move with real traffic."""
    rng = np.random.default_rng(8)
    pre = list(rng.integers(1, 200, 8))
    eng = ServingEngine(CFG, params, slots=2, cache_len=48, chunk=3,
                        prompt_buckets=(16,), kv_block_size=4)
    assert eng.kv_blocks_total() == 2 * (48 // 4)
    r1 = eng.submit(pre + [5, 6], 4)
    eng.run()
    hits0 = eng.kv_prefix_hit_tokens()
    r2 = eng.submit(pre + [7, 8, 9], 4)
    out = eng.run()
    assert out[r2][:len(pre)] == pre
    assert eng.kv_prefix_hit_tokens() - hits0 >= 8
    assert 0 < eng.kv_blocks_in_use() <= eng.kv_blocks_total()
    assert r1 != r2
