"""Sliding-window attention: oracle, chunked O(S·w) path, model wiring.

The window semantics are the Mistral convention — each query sees the
last ``window`` keys including itself.  ``local_attention_chunked`` must
match the exactly-masked oracle bit-for-tolerance, the dispatcher must
route combinations (packing, decode cache) to correctly masked paths,
and the Llama config plumbing must reach the layer.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # compile-heavy: full-suite tier

import jax
import jax.numpy as jnp

from tensorflow_train_distributed_tpu.ops.attention import (
    dot_product_attention,
    local_attention_chunked,
    multihead_attention_kernel,
)


def _qkv(rng, b=2, h=3, s=64, d=16, dtype=np.float32):
    def t():
        return jnp.asarray(rng.normal(0, 1, (b, h, s, d)).astype(dtype))

    return t(), t(), t()


class TestChunkedMatchesOracle:
    @pytest.mark.parametrize("s,w", [(64, 16), (128, 32), (48, 24),
                                     (64, 32)])
    def test_forward_parity(self, s, w):
        rng = np.random.default_rng(s + w)
        q, k, v = _qkv(rng, s=s)
        oracle = dot_product_attention(q, k, v, causal=True, window=w)
        got = local_attention_chunked(q, k, v, window=w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(oracle),
                                   rtol=2e-5, atol=2e-5)

    def test_gradient_parity(self):
        rng = np.random.default_rng(7)
        q, k, v = _qkv(rng, s=32, d=8)

        def loss_oracle(q, k, v):
            return jnp.sum(jnp.square(dot_product_attention(
                q, k, v, causal=True, window=8)))

        def loss_chunked(q, k, v):
            return jnp.sum(jnp.square(local_attention_chunked(
                q, k, v, window=8)))

        go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
        gc = jax.grad(loss_chunked, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(go, gc):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_first_window_matches_plain_causal(self):
        """Queries before the window fills see plain causal attention."""
        rng = np.random.default_rng(9)
        q, k, v = _qkv(rng, s=64)
        full = dot_product_attention(q, k, v, causal=True)
        win = local_attention_chunked(q, k, v, window=32)
        np.testing.assert_allclose(np.asarray(win)[..., :32, :],
                                   np.asarray(full)[..., :32, :],
                                   rtol=2e-5, atol=2e-5)
        # ...and later queries genuinely differ (the window binds).
        assert not np.allclose(np.asarray(win)[..., 32:, :],
                               np.asarray(full)[..., 32:, :], atol=1e-3)

    def test_rejects_indivisible(self):
        rng = np.random.default_rng(11)
        q, k, v = _qkv(rng, s=60)
        with pytest.raises(ValueError, match="divisible"):
            local_attention_chunked(q, k, v, window=16)


class TestDispatcher:
    def test_window_requires_causal(self):
        rng = np.random.default_rng(13)
        q, k, v = _qkv(rng, s=32)
        with pytest.raises(ValueError, match="causal"):
            multihead_attention_kernel(q, k, v, window=8)

    def test_window_with_packing_composes_masks(self):
        """Packed segments + window stay on the O(S·w) chunked path
        (segment ids ride the shift-concat) and match the dense-mask
        oracle composition exactly."""
        rng = np.random.default_rng(15)
        q, k, v = _qkv(rng, b=1, s=32)
        seg = jnp.asarray(
            np.repeat([1, 2], 16)[None, :])  # two 16-token documents
        got = multihead_attention_kernel(
            q, k, v, causal=True, segment_ids=seg, window=8)
        segmask = (seg[:, None, :, None] == seg[:, None, None, :])
        want = dot_product_attention(q, k, v, causal=True, mask=segmask,
                                     window=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        # And it really is the chunked path (identical, not just close).
        direct = local_attention_chunked(q, k, v, window=8,
                                         segment_ids=seg)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(direct))

    def test_uneven_doc_boundaries_in_chunked_path(self):
        """Doc boundaries that do NOT align with window chunks still
        mask exactly (ids shift-concat like the keys)."""
        rng = np.random.default_rng(16)
        q, k, v = _qkv(rng, b=2, s=64)
        lens = [(11, 29, 24), (5, 3, 56)]
        seg = jnp.asarray(np.stack([
            np.repeat(np.arange(1, len(l) + 1), l) for l in lens]))
        got = multihead_attention_kernel(
            q, k, v, causal=True, segment_ids=seg, window=16)
        segmask = (seg[:, None, :, None] == seg[:, None, None, :])
        want = dot_product_attention(q, k, v, causal=True, mask=segmask,
                                     window=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_window_zero_rejected(self):
        rng = np.random.default_rng(18)
        q, k, v = _qkv(rng, s=32)
        with pytest.raises(ValueError, match=">= 1"):
            multihead_attention_kernel(q, k, v, causal=True, window=0)

    def test_dense_fallback_warns_at_long_context(self):
        rng = np.random.default_rng(19)
        q, k, v = _qkv(rng, s=60)  # 60 % 14 != 0, 60 >= 4*14
        with pytest.warns(UserWarning, match="DENSE"):
            multihead_attention_kernel(q, k, v, causal=True, window=14)

    def test_kernel_window_routes_to_chunked(self):
        rng = np.random.default_rng(17)
        q, k, v = _qkv(rng, s=64)
        got = multihead_attention_kernel(q, k, v, causal=True, window=16)
        want = local_attention_chunked(q, k, v, window=16)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestLlamaSlidingWindow:
    def _cfgs(self):
        import dataclasses

        from tensorflow_train_distributed_tpu.models import llama

        base = llama.LLAMA_PRESETS["llama_tiny"]
        return base, dataclasses.replace(base, sliding_window=32)

    def test_short_sequences_match_full_attention(self):
        """S <= window: sliding window is vacuous, logits identical."""
        from tensorflow_train_distributed_tpu.models import llama

        full_cfg, win_cfg = self._cfgs()
        toks = jnp.asarray(
            np.random.default_rng(0).integers(0, 256, (2, 32)), jnp.int32)
        params = llama.LlamaModel(full_cfg).init(jax.random.key(0), toks)
        a = llama.LlamaModel(full_cfg).apply(params, toks)
        b = llama.LlamaModel(win_cfg).apply(params, toks)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def test_long_sequences_differ_and_train(self):
        import optax

        from tensorflow_train_distributed_tpu.models import llama

        full_cfg, win_cfg = self._cfgs()
        rng = np.random.default_rng(1)
        toks = jnp.asarray(rng.integers(0, 256, (2, 96)), jnp.int32)
        params = llama.LlamaModel(full_cfg).init(jax.random.key(0), toks)
        a = np.asarray(llama.LlamaModel(full_cfg).apply(params, toks))
        b = np.asarray(llama.LlamaModel(win_cfg).apply(params, toks))
        # The window binds beyond position 32 → different logits there.
        assert not np.allclose(a[:, 40:], b[:, 40:], atol=1e-3)
        # And a grad step is finite.
        task = llama.CausalLmTask(win_cfg)
        batch = {"tokens": np.asarray(toks),
                 "targets": rng.integers(0, 256, (2, 96)).astype(np.int32)}
        variables = task.init_variables(jax.random.key(0), batch)

        def loss(p):
            l, _ = task.loss_fn(p, {}, batch, jax.random.key(1), True)
            return l

        grads = jax.grad(loss)(variables["params"])
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree.leaves(grads))

    def test_decode_matches_teacher_forcing(self):
        """Greedy decode through the windowed KV cache reproduces the
        windowed model's full-forward argmax tokens."""
        from tensorflow_train_distributed_tpu.models import generate, llama

        _, win_cfg = self._cfgs()
        rng = np.random.default_rng(2)
        prompt = rng.integers(2, 256, (1, 48)).astype(np.int32)
        params = llama.LlamaModel(win_cfg).init(
            jax.random.key(0), jnp.asarray(prompt))["params"]
        out = generate.generate(win_cfg, params, prompt,
                                max_new_tokens=8)
        # Teacher-forced check: feeding the generated prefix reproduces
        # each next token via the full windowed forward.
        model = llama.LlamaModel(win_cfg)
        seq = np.asarray(out)
        for t in range(prompt.shape[1], seq.shape[1]):
            logits = model.apply({"params": params},
                                 jnp.asarray(seq[:, :t]))
            np.testing.assert_array_equal(
                np.argmax(np.asarray(logits)[:, -1], -1), seq[:, t])

    def test_rolling_cache_window_sized_and_wrap_exact(self):
        """cache_len > window → ring buffer of WINDOW rows per layer
        (the serving-memory win), and generation deep past several slot
        wraps still reproduces the windowed model's teacher-forced
        argmax stream."""
        import dataclasses

        import flax

        from tensorflow_train_distributed_tpu.models import generate, llama

        base = llama.LLAMA_PRESETS["llama_tiny"]
        cfg = dataclasses.replace(base, sliding_window=16)
        rng = np.random.default_rng(4)
        prompt = rng.integers(2, 256, (1, 20)).astype(np.int32)
        params = llama.LlamaModel(cfg).init(
            jax.random.key(0), jnp.asarray(prompt))["params"]
        # Cache buffers are window-sized, not request-sized.
        model = llama.LlamaModel(cfg, decode=True, cache_len=60)
        _, variables = model.apply({"params": params},
                                   jnp.asarray(prompt), mutable=["cache"])
        for path, leaf in flax.traverse_util.flatten_dict(
                dict(variables["cache"])).items():
            if path[-1] in ("key_cache", "value_cache"):
                assert leaf.shape[1] == 16, (path, leaf.shape)
        # 40 new tokens → positions to 59: slots wrap ~3.7 times.  One
        # causal forward teacher-forces every step at once: logits at
        # t-1 must argmax to the generated token t.
        out = np.asarray(generate.generate(cfg, params, prompt,
                                           max_new_tokens=40))
        logits = np.asarray(llama.LlamaModel(cfg).apply(
            {"params": params}, jnp.asarray(out)))
        p = prompt.shape[1]
        np.testing.assert_array_equal(
            np.argmax(logits[:, p - 1:-1], -1), out[:, p:])

    def test_rolling_chunked_prefill_matches_one_shot(self):
        """Multi-token calls at cur > 0 (chunked prefill) are exact under
        the rolling cache: feeding the prompt in two chunks produces the
        same logits and the same subsequent step logits as one prefill."""
        import dataclasses

        from tensorflow_train_distributed_tpu.models import llama

        cfg = dataclasses.replace(llama.LLAMA_PRESETS["llama_tiny"],
                                  sliding_window=8)
        rng = np.random.default_rng(5)
        prompt = jnp.asarray(rng.integers(2, 256, (1, 26)), jnp.int32)
        params = llama.LlamaModel(cfg).init(jax.random.key(0),
                                            prompt)["params"]
        model = llama.LlamaModel(cfg, decode=True, cache_len=40)
        one, v_one = model.apply({"params": params}, prompt,
                                 mutable=["cache"])
        a, va = model.apply({"params": params}, prompt[:, :11],
                            mutable=["cache"])
        b, vb = model.apply({"params": params, "cache": va["cache"]},
                            prompt[:, 11:], mutable=["cache"])
        np.testing.assert_allclose(
            np.asarray(one), np.concatenate([np.asarray(a),
                                             np.asarray(b)], axis=1),
            rtol=1e-5, atol=1e-5)
        # And the cache states agree for the NEXT step.
        tok = jnp.asarray([[7]], jnp.int32)
        s1, _ = model.apply({"params": params, "cache": v_one["cache"]},
                            tok, mutable=["cache"])
        s2, _ = model.apply({"params": params, "cache": vb["cache"]},
                            tok, mutable=["cache"])
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-5, atol=1e-5)

    def test_window_composes_with_seq_parallel(self):
        """Windowed llama trains under ring AND Ulysses SP with the
        SAME first-step loss as the unsharded windowed model — and the
        ring additionally skips out-of-window hops (if it skipped a
        NEEDED one, the losses would differ)."""
        import dataclasses

        import optax

        from tensorflow_train_distributed_tpu.models import llama
        from tensorflow_train_distributed_tpu.parallel.sharding import (
            shard_batch,
        )
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )
        from tensorflow_train_distributed_tpu.training import (
            Trainer, TrainerConfig,
        )

        rng = np.random.default_rng(3)
        batch = {"tokens": rng.integers(0, 256, (4, 64)).astype(np.int32),
                 "targets": rng.integers(0, 256,
                                         (4, 64)).astype(np.int32)}

        def first_loss(seq_parallel, mesh_cfg):
            import math

            cfg = dataclasses.replace(
                llama.LLAMA_PRESETS["llama_tiny"], sliding_window=16,
                seq_parallel=seq_parallel)
            n = math.prod(mesh_cfg.axis_sizes().values())
            mesh = build_mesh(mesh_cfg, devices=jax.devices()[:n])
            trainer = Trainer(llama.CausalLmTask(cfg), optax.adam(1e-3),
                              mesh, config=TrainerConfig(log_every=1))
            state = trainer.create_state(batch)
            step = trainer._compiled_train_step()
            _, metrics = step(state, shard_batch(mesh, batch))
            return float(metrics["loss"])

        base = first_loss(None, MeshConfig(data=2))
        ring = first_loss("ring", MeshConfig(data=2, seq=4))
        uly = first_loss("ulysses", MeshConfig(data=2, seq=2))
        assert base == pytest.approx(ring, rel=1e-4)
        assert base == pytest.approx(uly, rel=1e-4)

    def test_ring_window_parity_at_shard_boundaries(self):
        """shard_mapped ring attention with a window spanning shard
        boundaries matches the full windowed oracle (the skipped-hops
        optimization must keep every in-window key)."""
        from tensorflow_train_distributed_tpu.parallel.ring_attention \
            import shard_mapped_attention
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )

        mesh = build_mesh(MeshConfig(data=2, seq=4),
                          devices=jax.devices()[:8])
        rng = np.random.default_rng(6)
        q, k, v = _qkv(rng, b=2, h=4, s=64, d=8)
        for w in (8, 16, 24, 40):  # shard span 16: below/at/cross/2-hop
            out = shard_mapped_attention(mesh, q, k, v, method="ring",
                                         causal=True, window=w)
            ref = dot_product_attention(q, k, v, causal=True, window=w)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4,
                err_msg=f"window={w}")


class TestAttentionSinks:
    """StreamingLLM attention sinks: first-N positions stay attendable
    past the window — oracle, chunked path, decode sink buffers."""

    @pytest.mark.parametrize("s,w,sk", [(64, 16, 4), (64, 16, 16),
                                        (96, 32, 2)])
    def test_chunked_matches_oracle(self, s, w, sk):
        rng = np.random.default_rng(s + w + sk)
        q, k, v = _qkv(rng, s=s)
        want = dot_product_attention(q, k, v, causal=True, window=w,
                                     sinks=sk)
        got = local_attention_chunked(q, k, v, window=w, sinks=sk)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=2e-5)

    def test_sinks_actually_extend_reach(self):
        """Beyond the window, sink keys change the output vs plain SWA."""
        rng = np.random.default_rng(31)
        q, k, v = _qkv(rng, s=64)
        plain = local_attention_chunked(q, k, v, window=16)
        sunk = local_attention_chunked(q, k, v, window=16, sinks=4)
        # Early queries (window covers everything incl. sinks): equal.
        np.testing.assert_allclose(np.asarray(plain)[..., :16, :],
                                   np.asarray(sunk)[..., :16, :],
                                   rtol=1e-5, atol=1e-5)
        assert not np.allclose(np.asarray(plain)[..., 32:, :],
                               np.asarray(sunk)[..., 32:, :], atol=1e-3)

    def test_sinks_require_window(self):
        rng = np.random.default_rng(33)
        q, k, v = _qkv(rng, s=32)
        with pytest.raises(ValueError, match="sliding window"):
            multihead_attention_kernel(q, k, v, causal=True, sinks=2)

    def test_packed_sinks_compose(self):
        rng = np.random.default_rng(35)
        q, k, v = _qkv(rng, b=1, s=64)
        seg = jnp.asarray(np.repeat([1, 2], 32)[None, :])
        got = multihead_attention_kernel(
            q, k, v, causal=True, window=16, sinks=4, segment_ids=seg)
        segmask = (seg[:, None, :, None] == seg[:, None, None, :])
        want = dot_product_attention(q, k, v, causal=True, window=16,
                                     sinks=4, mask=segmask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=2e-5)

    def test_streaming_decode_teacher_forcing_exact(self):
        """Generation deep past the window with sink buffers + rolling
        ring reproduces the full-forward argmax stream (several slot
        wraps; the sink buffer carries positions the ring evicted)."""
        import dataclasses

        from tensorflow_train_distributed_tpu.models import generate, llama

        cfg = dataclasses.replace(llama.LLAMA_PRESETS["llama_tiny"],
                                  sliding_window=16, attention_sinks=4)
        rng = np.random.default_rng(37)
        prompt = rng.integers(2, 256, (1, 24)).astype(np.int32)
        params = llama.LlamaModel(cfg).init(
            jax.random.key(0), jnp.asarray(prompt))["params"]
        out = np.asarray(generate.generate(cfg, params, prompt,
                                           max_new_tokens=40))
        logits = np.asarray(llama.LlamaModel(cfg).apply(
            {"params": params}, jnp.asarray(out)))
        p = prompt.shape[1]
        np.testing.assert_array_equal(
            np.argmax(logits[:, p - 1:-1], -1), out[:, p:])

    def test_chunked_prefill_with_sinks_matches_one_shot(self):
        import dataclasses

        from tensorflow_train_distributed_tpu.models import llama

        cfg = dataclasses.replace(llama.LLAMA_PRESETS["llama_tiny"],
                                  sliding_window=8, attention_sinks=3)
        rng = np.random.default_rng(39)
        prompt = jnp.asarray(rng.integers(2, 256, (1, 26)), jnp.int32)
        params = llama.LlamaModel(cfg).init(jax.random.key(0),
                                            prompt)["params"]
        model = llama.LlamaModel(cfg, decode=True, cache_len=40)
        one, v_one = model.apply({"params": params}, prompt,
                                 mutable=["cache"])
        a, va = model.apply({"params": params}, prompt[:, :11],
                            mutable=["cache"])
        b, vb = model.apply({"params": params, "cache": va["cache"]},
                            prompt[:, 11:], mutable=["cache"])
        np.testing.assert_allclose(
            np.asarray(one),
            np.concatenate([np.asarray(a), np.asarray(b)], axis=1),
            rtol=1e-5, atol=1e-5)
        tok = jnp.asarray([[9]], jnp.int32)
        s1, _ = model.apply({"params": params, "cache": v_one["cache"]},
                            tok, mutable=["cache"])
        s2, _ = model.apply({"params": params, "cache": vb["cache"]},
                            tok, mutable=["cache"])
        np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                                   rtol=1e-5, atol=1e-5)

    def test_streaming_from_single_token_prompt(self):
        """Degenerate-but-legal: prompt SHORTER than the sink count.
        The sink buffer fills incrementally as positions decode (masked
        merge), exclusivity holds at every cur, and the stream still
        teacher-forces exactly."""
        import dataclasses

        from tensorflow_train_distributed_tpu.models import generate, llama

        cfg = dataclasses.replace(llama.LLAMA_PRESETS["llama_tiny"],
                                  sliding_window=8, attention_sinks=4)
        prompt = np.asarray([[5]], np.int32)
        params = llama.LlamaModel(cfg).init(
            jax.random.key(0), jnp.asarray(prompt))["params"]
        out = np.asarray(generate.generate(cfg, params, prompt,
                                           max_new_tokens=30))
        logits = np.asarray(llama.LlamaModel(cfg).apply(
            {"params": params}, jnp.asarray(out)))
        np.testing.assert_array_equal(
            np.argmax(logits[:, :-1], -1), out[:, 1:])

    @pytest.mark.parametrize("sk", [2, 8, 16])
    def test_ring_sp_sinks_match_oracle(self, sk):
        """Ring SP + sinks: shard 0's sink block broadcasts (tiny psum)
        and every shard folds it into the online softmax — matches the
        full windowed+sinks oracle at sink counts below/at the shard
        span (span 16 on a 4-way seq axis over S=64)."""
        from tensorflow_train_distributed_tpu.parallel.ring_attention \
            import shard_mapped_attention
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )

        mesh = build_mesh(MeshConfig(data=2, seq=4),
                          devices=jax.devices()[:8])
        rng = np.random.default_rng(43 + sk)
        q, k, v = _qkv(rng, b=2, h=4, s=64, d=8)
        out = shard_mapped_attention(mesh, q, k, v, method="ring",
                                     causal=True, window=24, sinks=sk)
        ref = dot_product_attention(q, k, v, causal=True, window=24,
                                    sinks=sk)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)

    def test_ring_sp_sinks_with_packing(self):
        from tensorflow_train_distributed_tpu.parallel.ring_attention \
            import shard_mapped_attention
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )

        mesh = build_mesh(MeshConfig(data=2, seq=4),
                          devices=jax.devices()[:8])
        rng = np.random.default_rng(47)
        q, k, v = _qkv(rng, b=2, h=4, s=64, d=8)
        seg = jnp.asarray(np.stack([
            np.repeat([1, 2], [30, 34]), np.repeat([1, 2], [10, 54])]))
        out = shard_mapped_attention(mesh, q, k, v, method="ring",
                                     causal=True, window=24, sinks=4,
                                     segment_ids=seg)
        segmask = (seg[:, None, :, None] == seg[:, None, None, :])
        ref = dot_product_attention(q, k, v, causal=True, window=24,
                                    sinks=4, mask=segmask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)

    def test_ring_sp_sinks_exceeding_shard_rejected(self):
        from tensorflow_train_distributed_tpu.parallel.ring_attention \
            import shard_mapped_attention
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            MeshConfig, build_mesh,
        )

        mesh = build_mesh(MeshConfig(data=2, seq=4),
                          devices=jax.devices()[:8])
        rng = np.random.default_rng(49)
        q, k, v = _qkv(rng, b=2, h=4, s=64, d=8)
        with pytest.raises(ValueError, match="shard"):
            shard_mapped_attention(mesh, q, k, v, method="ring",
                                   causal=True, window=24, sinks=20)


def test_cli_trains_windowed_family():
    """The registered mistral-shaped config (sliding window + sinks)
    trains through the real CLI."""
    from tensorflow_train_distributed_tpu import launch

    result = launch.run(launch.build_parser().parse_args([
        "--config", "mistral_tiny_lm", "--steps", "3",
        "--global-batch-size", "8", "--platform", "cpu",
        "--log-every", "1"]))
    assert np.isfinite(result.history["loss"]).all()


class TestSplashWindow:
    """The TPU splash-kernel route for sliding windows, validated in
    pallas interpret mode on CPU against the exact masked oracle."""

    def test_forward_parity_interpret(self):
        from tensorflow_train_distributed_tpu.ops.attention import (
            dot_product_attention,
            splash_window_attention,
        )

        rng = np.random.default_rng(0)
        b, h, s, d, w = 1, 2, 256, 64, 64
        q, k, v = (jnp.asarray(rng.normal(0, 1, (b, h, s, d)),
                               jnp.float32) for _ in range(3))
        want = dot_product_attention(q, k, v, causal=True, window=w)
        got = splash_window_attention(q, k, v, window=w, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)

    def test_segment_ids_parity_interpret(self):
        from tensorflow_train_distributed_tpu.ops.attention import (
            multihead_attention_kernel,
            splash_window_attention,
        )

        rng = np.random.default_rng(1)
        b, h, s, d, w = 1, 2, 256, 64, 64
        q, k, v = (jnp.asarray(rng.normal(0, 1, (b, h, s, d)),
                               jnp.float32) for _ in range(3))
        seg = jnp.asarray(
            np.repeat([1, 1, 2, 2], s // 4)[None, :], jnp.int32)
        # Oracle: the exactly-masked reference path (force_reference).
        want = multihead_attention_kernel(
            q, k, v, causal=True, window=w, segment_ids=seg,
            force_reference=True)
        got = splash_window_attention(q, k, v, window=w,
                                      segment_ids=seg, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)

    def test_gradient_parity_interpret(self):
        from tensorflow_train_distributed_tpu.ops.attention import (
            dot_product_attention,
            splash_window_attention,
        )

        rng = np.random.default_rng(2)
        b, h, s, d, w = 1, 1, 256, 64, 64
        q, k, v = (jnp.asarray(rng.normal(0, 1, (b, h, s, d)),
                               jnp.float32) for _ in range(3))

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(
                q, k, v, causal=True, window=w) ** 2)

        def loss_splash(q, k, v):
            return jnp.sum(splash_window_attention(
                q, k, v, window=w, interpret=True) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_spl = jax.grad(loss_splash, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_spl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-3, atol=5e-3)

    def test_splash_opt_in_and_kill_switch(self, monkeypatch):
        """Splash is OPT-IN (TTD_SPLASH=1): chunked beat it on silicon
        at the measured shape (PROFILE.md round-4), so the measured
        winner is the default.  On CPU the splash route never fires;
        TTD_NO_SPLASH still wins over TTD_SPLASH (kill switch); and
        0/false/empty mean OFF for both flags (the TTD_NO_PALLAS
        lesson)."""
        from tensorflow_train_distributed_tpu.ops import attention

        monkeypatch.delenv("TTD_NO_SPLASH", raising=False)  # dev shells
        monkeypatch.delenv("TTD_SPLASH", raising=False)
        q = jnp.zeros((1, 2, 256, 64))
        args = dict(sinks=0, mask=None, force_reference=False)
        assert not attention._splash_window_friendly(q, q, **args)  # cpu
        # Fake a TPU backend: the shape/dtype gates pass, so the env
        # flags are what the next assertions exercise.
        monkeypatch.setattr(attention.jax, "default_backend",
                            lambda: "tpu")
        assert not attention._splash_window_friendly(q, q, **args)  # opt-in
        monkeypatch.setenv("TTD_SPLASH", "1")
        assert attention._splash_window_friendly(q, q, **args)
        monkeypatch.setenv("TTD_NO_SPLASH", "1")  # kill switch wins
        assert not attention._splash_window_friendly(q, q, **args)
        monkeypatch.setenv("TTD_NO_SPLASH", "0")
        assert attention._splash_window_friendly(q, q, **args)
        monkeypatch.setenv("TTD_SPLASH", "false")
        assert not attention._splash_window_friendly(q, q, **args)
